"""Intra-op (model-axis) decomposition — the TPU-native analog of the MPI
kernel library (SURVEY.md §2.2 C15, §2.3).

The reference's MPI backend partitions each kernel's *output index space*
across ranks and sums partial results with `MPI_Reduce` (partition formula
at MPI/layer.h:172-175; 16 reduce sites). Translated to a TPU mesh, the
same capability becomes *sharded parameters + XLA collectives over ICI*,
composed with data parallelism on a 2-D (data, model) mesh — the "hybrid"
the reference names only as future work (README.md:24, PDF §8):

- conv c1: the 6 filters are sharded over ``model`` — each device computes
  its feature maps only (≙ the MPI split of fp_c1's output space,
  MPI/layer.h:162-201, minus bugs B1/B2).
- pool s1: channel-local, so it inherits the conv's channel sharding with
  NO communication (the reference re-reduces every kernel anyway — 18
  collectives per sample, PDF §7.1's scalability killer; here the only
  forward collective is the FC psum).
- fc f: the 216-wide contraction is sharded over ``model`` (the flattened
  (6,6,6) input is channel-major, so the channel shard IS a contiguous
  slice of the contraction dim); partial products are `psum`ed — the
  direct, correct form of the MPI partial-result+reduce pattern
  (MPI/layer.h:345-368), with the broadcast-back the reference forgot (B7).

Backward follows the same shardings; only three collectives appear per
step and XLA schedules them onto ICI: psum(pre_f), psum(g_w_s1 ⊕ g_b_s1 ⊕
misc scalars), psum over the data axis for DP.

Legal model-axis sizes divide 6 (the filter count): 1, 2, 3, 6.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from parallel_cnn_tpu.ops import reference as ops
from parallel_cnn_tpu.ops.activations import (
    apply_grad,
    error_norm,
    make_error,
    sigmoid,
    sigmoid_grad_from_preact,
)
from parallel_cnn_tpu.parallel import collectives
from parallel_cnn_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, shard_map

Params = ops.Params

# How the params pytree is laid out over the (data, model) mesh: conv
# filters and the FC contraction dim ride the model axis, everything else
# is replicated.
PARAM_SPECS: Params = {
    "c1": {"w": P(MODEL_AXIS), "b": P(MODEL_AXIS)},
    "s1": {"w": P(), "b": P()},
    "f": {"w": P(None, MODEL_AXIS), "b": P()},
}


def param_shardings(mesh: Mesh) -> Params:
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        PARAM_SPECS,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(mesh: Mesh, params: Params) -> Params:
    """Place a (host or replicated) params pytree into its 2-D layout.

    Copies first: the train step donates params, and device_put may alias
    the source buffer when it already lives on a mesh device.
    """
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(jnp.array(x), s), params, param_shardings(mesh)
    )


def _forward_local(params: Params, x: jax.Array):
    """Single-sample forward on one (data, model) shard.

    x: (28, 28) replicated over model; params already model-sharded, so
    w_c1 is (6/m, 5, 5) and w_f is (10, 216/m) *inside* shard_map.
    """
    pre_c1 = ops.conv_c1_forward(x, params["c1"]["w"], params["c1"]["b"])
    out_c1 = sigmoid(pre_c1)                       # (6/m, 24, 24) local channels
    pre_s1 = ops.pool_s1_forward(out_c1, params["s1"]["w"], params["s1"]["b"])
    out_s1 = sigmoid(pre_s1)                       # (6/m, 6, 6) local channels
    # Sharded 216-contraction: local (10, 216/m) @ local (216/m,) then psum
    # — partial-product + allreduce, the corrected MPI fp_preact_f pattern.
    partial = params["f"]["w"] @ out_s1.reshape(-1)
    pre_f = lax.psum(partial, MODEL_AXIS) + params["f"]["b"]
    out_f = sigmoid(pre_f)
    return pre_c1, out_c1, pre_s1, out_s1, pre_f, out_f


def _backward_local(params: Params, x, acts, label):
    """Reference-contract backward (ops/reference.py:backward) under the
    model sharding. Collectives: one fused psum for the shared-kernel grads."""
    pre_c1, out_c1, pre_s1, out_s1, pre_f, out_f = acts
    cm = out_c1.shape[0]

    d_pre_f = make_error(out_f, label)             # replicated over model
    err = error_norm(d_pre_f)

    # FC grads: outer product is naturally sharded over the contraction dim.
    g_w_f = jnp.outer(d_pre_f, out_s1.reshape(-1))     # (10, 216/m) local
    g_b_f = d_pre_f

    # Pool backward: each model shard only needs ITS columns of w_f.
    d_out_s1 = (params["f"]["w"].T @ d_pre_f).reshape(cm, 6, 6)
    d_pre_s1 = d_out_s1 * sigmoid_grad_from_preact(pre_s1)
    # Shared 4×4 kernel + scalar bias: contractions over ALL channels →
    # psum over model (≙ MPI bp_weight_s1's reduce, minus bug B5).
    out_c1_windows = out_c1.reshape(cm, 6, 4, 6, 4)
    g_w_s1_partial = jnp.einsum("mxy,mxiyj->ij", d_pre_s1, out_c1_windows)
    g_b_s1_partial = jnp.sum(d_pre_s1) / ops.POOL_BIAS_NORM
    g_w_s1, g_b_s1 = lax.psum((g_w_s1_partial, g_b_s1_partial), MODEL_AXIS)

    # Conv backward: channel-local throughout (filters are model-sharded).
    d_out_c1 = jnp.einsum(
        "mxy,ij->mxiyj", d_pre_s1, params["s1"]["w"]
    ).reshape(cm, 24, 24)
    d_pre_c1 = d_out_c1 * sigmoid_grad_from_preact(pre_c1)
    patches = lax.conv_general_dilated_patches(
        x[None, None, :, :], (5, 5), (1, 1), "VALID"
    )[0]                                            # (25, 24, 24), replicated
    g_w_c1 = (
        jnp.einsum("mxy,pxy->mp", d_pre_c1, patches).reshape(cm, 5, 5)
        / ops.CONV_NORM
    )
    g_b_c1 = jnp.sum(d_pre_c1, axis=(1, 2)) / ops.CONV_NORM

    grads: Params = {
        "c1": {"w": g_w_c1, "b": g_b_c1},
        "s1": {"w": g_w_s1, "b": g_b_s1},
        "f": {"w": g_w_f, "b": g_b_f},
    }
    return err, grads


def _sample_grads(params: Params, x: jax.Array, y: jax.Array):
    acts = _forward_local(params, x)
    return _backward_local(params, x, acts, y)


def make_2d_step(mesh: Mesh, dt: float, global_batch: int,
                 compute_dtype: str | None = None, comm=None):
    """Hybrid DP×model-parallel train step over the full 2-D mesh.

    params follow PARAM_SPECS; x:(B,28,28) / y:(B,) are sharded over the
    data axis and replicated over model. One jitted program; grads are
    allreduced over ``data`` (DP) while activations/grads inside each
    sample are decomposed over ``model`` (intra-op). ``comm`` (a
    config.CommConfig) picks the data-axis grad-reduce algorithm
    (collectives.tree_all_reduce); None is the historical monolithic
    psum. The model-axis activation collectives always stay psum — they
    are small and latency-bound, exactly where a ring loses.

    compute_dtype="bfloat16": the per-sample forward/backward (including
    the model-axis activation psum) runs bf16; grads are cast back to f32
    BEFORE the data-axis reduce, and params stay f32 master weights — the
    same mixed-precision recipe as train/step.py batched_step, composed
    with both mesh axes.
    """

    n_data = mesh.shape[DATA_AXIS]
    cdt = jnp.dtype(compute_dtype or "float32")

    def shard_body(params: Params, x: jax.Array, y: jax.Array):
        if x.shape[0] * n_data != global_batch:
            raise ValueError(
                f"batch {x.shape[0] * n_data} != global_batch {global_batch}"
            )
        cparams = jax.tree_util.tree_map(lambda p: p.astype(cdt), params)
        errs, grads = jax.vmap(_sample_grads, in_axes=(None, 0, 0))(
            cparams, x.astype(cdt), y
        )
        err_sum = lax.psum(jnp.sum(errs.astype(jnp.float32)), DATA_AXIS)
        local_sums = jax.tree_util.tree_map(
            lambda g: jnp.sum(g.astype(jnp.float32), axis=0), grads
        )
        grad_sum = collectives.tree_all_reduce(
            local_sums, DATA_AXIS, n_data, comm
        )
        mean_grads = jax.tree_util.tree_map(lambda g: g / global_batch, grad_sum)
        return apply_grad(params, mean_grads, dt), err_sum / global_batch

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(PARAM_SPECS, P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(PARAM_SPECS, P()),
        check_vma=(comm is None or comm.impl != "ring"),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_2d_forward(mesh: Mesh):
    """Batched model-parallel inference over the 2-D mesh → (B, 10) outputs."""

    def shard_body(params: Params, x: jax.Array):
        out = jax.vmap(lambda s: _forward_local(params, s)[-1])(x)
        return out

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(PARAM_SPECS, P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
    )
    return jax.jit(sharded)
