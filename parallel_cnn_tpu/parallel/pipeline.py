"""Pipeline-parallel substrate: stage partitioning + the 1F1B schedule.

The reference's MPI backend decomposes each kernel across ranks; the
scale-out direction it gestures at (and arXiv:1711.00705 /
arXiv:1810.11112 analyze) is partitioning the MODEL across devices.
This module is the static half of that axis:

- ``split_layers`` chooses stage boundaries by balancing per-layer flops
  from the PR 8 cost accountant's measured tables
  (analysis/cost_model.measured_flops over each layer's jaxpr) — the
  same numbers `check --cost` verifies, so the splitter and the gate
  share one source of truth;
- ``schedule_events`` is the closed-form 1F1B tick table the traced step
  (train/pipeline_schedule.py) compiles against: forward of microbatch m
  at stage s fires at tick ``s + 2m``, its backward at tick
  ``2S − 1 − s + 2m``, giving warmup/steady/cooldown with at most S live
  stashed microbatches per stage and a bubble fraction of
  (S−1)/(S−1+M);
- the pack/unpack helpers flatten stage-boundary activations into one
  uniform zero-padded ``(microbatch, A_buf)`` wire buffer so every
  stage's send/recv has identical type regardless of which layer's
  output crosses the boundary (the uniformity `lax.switch` needs).

Everything here is host-side Python over static shapes — no jax tracing
happens at import, and the schedule is a pure function of (S, M) so
tests can pin its event order exactly.
"""

from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from parallel_cnn_tpu.nn.core import Module


# ---------------------------------------------------------------------------
# 1F1B schedule (closed form)
# ---------------------------------------------------------------------------

class TickEvent(NamedTuple):
    """One synchronous tick: per-stage microbatch ids (None = idle).

    ``fwd[s]`` is the microbatch whose forward stage s runs this tick;
    ``bwd[s]`` the microbatch whose backward it runs. The closed form
    gives each stage disjoint fwd/bwd tick parities, so a stage never
    does both in one tick.
    """

    fwd: Tuple[Optional[int], ...]
    bwd: Tuple[Optional[int], ...]


def n_ticks(n_stages: int, n_micro: int) -> int:
    """Total ticks of the 1F1B schedule: 2·(M + S − 1)."""
    return 2 * (n_micro + n_stages - 1)


def bubble_fraction(n_stages: int, n_micro: int) -> float:
    """Idle fraction per stage: (S−1)/(S−1+M) — the GPipe bubble law.

    Each stage works 2M of the 2(M+S−1) ticks (M forwards + M
    backwards), so the idle share is (S−1)/(M+S−1) regardless of s.
    """
    return (n_stages - 1) / (n_stages - 1 + n_micro)


def schedule_events(n_stages: int, n_micro: int) -> Tuple[TickEvent, ...]:
    """The deterministic 1F1B tick table for S stages × M microbatches.

    Closed form: Tf(s, m) = s + 2m and Tb(s, m) = 2S − 1 − s + 2m.
    Consequences the traced step and the tests rely on:

    - producer/consumer latency is exactly one tick on both wires
      (Tf(s+1, m) = Tf(s, m) + 1; Tb(s, m) = Tb(s+1, m) + 1), matching
      the one-ppermute-per-tick send/recv;
    - a stage's fwd ticks have parity s, its bwd ticks parity s+1 —
      never both in one tick;
    - stash slot ``m mod S`` is reuse-safe: Tf(s, m+S) − Tb(s, m) =
      2s + 1 > 0, so microbatch m's stashed input is consumed strictly
      before microbatch m+S overwrites the slot.
    """
    if n_stages < 1:
        raise ValueError(f"n_stages must be >= 1, got {n_stages}")
    if n_micro < 1:
        raise ValueError(f"n_micro must be >= 1, got {n_micro}")
    events = []
    for t in range(n_ticks(n_stages, n_micro)):
        fwd: List[Optional[int]] = []
        bwd: List[Optional[int]] = []
        for s in range(n_stages):
            df = t - s
            fwd.append(df // 2 if df >= 0 and df % 2 == 0
                       and df // 2 < n_micro else None)
            db = t - (2 * n_stages - 1 - s)
            bwd.append(db // 2 if db >= 0 and db % 2 == 0
                       and db // 2 < n_micro else None)
        events.append(TickEvent(tuple(fwd), tuple(bwd)))
    return tuple(events)


def schedule_arrays(n_stages: int, n_micro: int):
    """The schedule as (T, S) numpy constants for the traced step.

    Returns (fwd_mb, fwd_valid, bwd_mb, bwd_valid): int32 microbatch ids
    (idle entries clamped to 0 — the valid masks gate every use) and
    bool validity masks. np constants, not Python ints, so the traced
    step's `where` masks never introduce weak types.
    """
    events = schedule_events(n_stages, n_micro)
    t_total = len(events)
    fwd_mb = np.zeros((t_total, n_stages), np.int32)
    fwd_valid = np.zeros((t_total, n_stages), bool)
    bwd_mb = np.zeros((t_total, n_stages), np.int32)
    bwd_valid = np.zeros((t_total, n_stages), bool)
    for t, ev in enumerate(events):
        for s in range(n_stages):
            if ev.fwd[s] is not None:
                fwd_mb[t, s] = ev.fwd[s]
                fwd_valid[t, s] = True
            if ev.bwd[s] is not None:
                bwd_mb[t, s] = ev.bwd[s]
                bwd_valid[t, s] = True
    return fwd_mb, fwd_valid, bwd_mb, bwd_valid


def stash_high_water(n_stages: int, n_micro: int) -> int:
    """Max simultaneously-stashed microbatches at any stage (simulated).

    The 1F1B bound: never exceeds n_stages (tests/test_pipeline.py pins
    it) — the whole point of 1F1B over all-forward-then-all-backward
    GPipe, whose stash grows with M instead.
    """
    peak = 0
    for s in range(n_stages):
        live = set()
        for ev in schedule_events(n_stages, n_micro):
            if ev.fwd[s] is not None:
                live.add(ev.fwd[s])
                peak = max(peak, len(live))
            if ev.bwd[s] is not None:
                live.discard(ev.bwd[s])
    return peak


# ---------------------------------------------------------------------------
# Cost-model-driven stage splitting
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class LayerCost:
    """Per-layer static cost row (the splitter's input; also surfaced by
    `--suite pipeline` so the balance decision is auditable)."""

    index: int
    name: str
    flops: int          # measured_flops of this layer's fwd jaxpr
    param_bytes: int    # trainable residency
    out_shape: Tuple[int, ...]  # batched output (microbatch leading)
    out_numel: int      # per-SAMPLE activation numel (wire payload unit)


def _tree_bytes(tree) -> int:
    return sum(
        int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
        for x in jax.tree_util.tree_leaves(tree)
    )


def layer_costs(model: Module, in_shape: Sequence[int],
                microbatch: int = 1) -> Tuple[LayerCost, ...]:
    """Per-layer flops/bytes/output table from the cost accountant.

    Each layer's forward is traced in isolation at the microbatch shape
    and its contraction flops counted by the same
    cost_model.measured_flops walk `check --cost` uses — the splitter
    balances exactly the numbers the gate verifies. Shape-only: params
    come from a fixed-seed init and never execute.
    """
    from parallel_cnn_tpu.analysis.cost_model import measured_flops

    params, state, _ = model.init(jax.random.PRNGKey(0), tuple(in_shape))
    rows = []
    shape = tuple(in_shape)
    for i, (layer, p, s) in enumerate(zip(model.layers, params, state)):
        x = jax.ShapeDtypeStruct((microbatch,) + shape, jnp.float32)

        def fwd(xx, layer=layer, p=p, s=s):
            return layer.apply(p, s, xx, train=True)[0]

        closed = jax.make_jaxpr(fwd)(x)
        out = jax.eval_shape(fwd, x)
        rows.append(LayerCost(
            index=i,
            name=type(layer).__name__,
            flops=int(measured_flops(closed)),
            param_bytes=_tree_bytes(p),
            out_shape=tuple(out.shape),
            out_numel=int(np.prod(out.shape[1:])),
        ))
        shape = tuple(out.shape[1:])
    return tuple(rows)


def split_layers(model: Module, n_stages: int, in_shape: Sequence[int],
                 microbatch: int = 1,
                 boundaries: Sequence[int] = ()) -> Tuple[int, ...]:
    """Choose stage-start boundaries (S−1 strictly-increasing layer
    indices in [1, L−1]) for a contiguous S-way partition of the model.

    Automatic mode (no ``boundaries``): dynamic programming over
    contiguous partitions minimizing the maximum per-stage flops —
    the pipeline's steady-state throughput is set by its slowest stage —
    with maximum per-stage param bytes as the tie-break (prefer the
    split that also levels residency). Manual mode validates the given
    boundaries against the layer count and returns them sorted.
    """
    n_layers = len(model.layers)
    if n_stages < 1:
        raise ValueError(f"n_stages must be >= 1, got {n_stages}")
    if n_stages > n_layers:
        raise ValueError(
            f"cannot split {n_layers} layers into {n_stages} stages "
            "(every stage needs at least one layer)"
        )
    if boundaries:
        b = tuple(sorted(int(x) for x in boundaries))
        if len(b) != n_stages - 1:
            raise ValueError(
                f"{len(b)} boundaries cannot make {n_stages} stages "
                f"(need {n_stages - 1})"
            )
        if len(set(b)) != len(b) or b[0] < 1 or b[-1] > n_layers - 1:
            raise ValueError(
                f"boundaries {b} must be distinct layer indices in "
                f"[1, {n_layers - 1}]"
            )
        return b
    if n_stages == 1:
        return ()

    costs = layer_costs(model, in_shape, microbatch)
    flops = [c.flops for c in costs]
    pbytes = [c.param_bytes for c in costs]
    pref_f = np.concatenate([[0], np.cumsum(flops)])
    pref_b = np.concatenate([[0], np.cumsum(pbytes)])

    def seg(pref, a, b):  # cost of layers [a, b)
        return int(pref[b] - pref[a])

    # best[k][j] = (max_flops, max_bytes, boundaries) for splitting the
    # first j layers into k stages. L and S are tiny (≤ dozens), so the
    # O(S·L²) table is free.
    best = {(1, j): (seg(pref_f, 0, j), seg(pref_b, 0, j), ())
            for j in range(1, n_layers + 1)}
    for k in range(2, n_stages + 1):
        for j in range(k, n_layers + 1):
            cand = None
            for i in range(k - 1, j):
                mf, mb, bs = best[(k - 1, i)]
                key = (max(mf, seg(pref_f, i, j)),
                       max(mb, seg(pref_b, i, j)))
                if cand is None or key < cand[:2]:
                    cand = (*key, bs + (i,))
            best[(k, j)] = cand
    return best[(n_stages, n_layers)][2]


def stage_assignment(n_layers: int,
                     boundaries: Sequence[int]) -> np.ndarray:
    """Layer-index → stage-index map (int32, length n_layers)."""
    assign = np.zeros(n_layers, np.int32)
    for b in boundaries:
        assign[b:] += 1
    return assign


# ---------------------------------------------------------------------------
# Stage-boundary wire buffers
# ---------------------------------------------------------------------------

def boundary_shapes(model: Module, in_shape: Sequence[int],
                    boundaries: Sequence[int],
                    microbatch: int) -> Tuple[Tuple[int, ...], ...]:
    """Batched activation shape crossing each stage boundary: the output
    of the last layer of stages 0..S−2, at the microbatch size."""
    costs = layer_costs(model, in_shape, microbatch)
    return tuple(costs[b - 1].out_shape for b in boundaries)


def wire_numel(model: Module, in_shape: Sequence[int],
               boundaries: Sequence[int], microbatch: int) -> int:
    """A_buf: the uniform per-microbatch wire/stash width — max
    per-sample numel over every stage boundary AND the model input (the
    first-stage branch packs its image microbatch through the same
    buffer so all `lax.switch` branches stay type-uniform)."""
    numels = [int(np.prod(tuple(in_shape)))]
    costs = layer_costs(model, in_shape, microbatch)
    numels += [costs[b - 1].out_numel for b in boundaries]
    return max(numels)


def pack_acts(x: jax.Array, a_buf: int) -> jax.Array:
    """Flatten a batched activation to (batch, A_buf), zero-padded."""
    flat = x.reshape(x.shape[0], -1)
    pad = a_buf - flat.shape[1]
    if pad < 0:
        raise ValueError(
            f"activation numel {flat.shape[1]} exceeds wire width {a_buf}"
        )
    if pad == 0:
        return flat
    return jnp.pad(flat, ((0, 0), (0, pad)))


def unpack_acts(buf: jax.Array, shape: Sequence[int]) -> jax.Array:
    """Recover a batched activation from its packed wire buffer."""
    shape = tuple(shape)
    numel = int(np.prod(shape[1:]))
    return buf[:, :numel].reshape(shape)
