"""Parallelism layer: device meshes, data-parallel and intra-op (model)
sharded training — the TPU-native counterpart of the reference's OpenMP /
MPI / CUDA backends (SURVEY.md §2.3, §2.4)."""

from parallel_cnn_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    distributed_init,
    make_mesh,
    pad_to_multiple,
    replicate,
    replicated,
    shard_batch,
    single_device_mesh,
)
from parallel_cnn_tpu.parallel import data_parallel, intra_op  # noqa: F401
