"""Bucketed gradient collectives: explicit ring reduce-scatter/all-gather
over the mesh's ``data`` axis, with optional bf16-on-the-wire compression.

Both trainers reduce the whole grad pytree in ONE ``psum`` at the end of
backward (parallel/data_parallel.py, train/zoo.py) — semantically a true
allreduce (the corrected version of the reference MPI backend's 16
root-only reduces, SURVEY.md B7), but a single monolithic collective gives
the scheduler nothing to overlap: ICI idles during compute and compute
idles during the reduce. This module provides the standard latency-hiding
decomposition (arXiv:1810.11112, arXiv:1605.08325, Horovod-style):

- **bucketization** — the grad pytree is flattened into fixed-byte 1-D
  buckets (`plan_buckets` / `flatten_buckets` / `unflatten_buckets`) with
  an exact round-trip: leaves grouped by dtype (so concatenation is
  bit-preserving for float AND integer leaves), scalars and odd shapes
  raveled in, zero-size leaves carried in metadata only, and each bucket
  zero-padded to a multiple of the axis size so ring chunks stay even;
- **ring collectives** — `ring_reduce_scatter` + `ring_all_gather` built
  from `lax.ppermute` (run inside shard_map), the bandwidth-optimal
  2(n−1)/n-payload alternative to monolithic psum (docs/collectives.md);
- **wire-dtype compression** — float payloads optionally cast to bf16 for
  the hop transfers while every accumulation stays f32 master precision;
- **selection** — `tree_all_reduce` dispatches on a config.CommConfig
  (impl "psum" keeps the monolithic collective; "ring" goes bucketed),
  so callers hold one code path and the choice rides PCNN_COMM_IMPL /
  --comm-impl.

The overlap schedule itself lives in the grad-accumulation consumer
(train/zoo.py): each microbatch's buckets are reduce-scattered as soon as
its grads are final — kept OUT of the inter-microbatch optimization
barrier — and one all-gather at the end rematerializes the full grads.

Numerics contract: ring reduction reassociates the f32 sum (n partial
orders instead of psum's fixed tree), so results match psum to roundoff
(~1e-5 relative for zoo-scale grads), not bit-exactly; bf16 wire adds a
per-hop requantization, keeping loss parity to ~1e-2. Both bounds are
pinned by tests/test_collectives.py and the MULTICHIP dryrun leg.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

DEFAULT_BUCKET_BYTES = 4 * 1024 * 1024  # PCNN_COMM_BUCKET_BYTES default


# --------------------------------------------------------------------------
# Bucketization: pytree <-> list of fixed-byte 1-D buffers, exact round-trip
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LeafSlot:
    """Where one pytree leaf lives inside the bucket list.

    ``bucket == -1`` marks a zero-size leaf: it occupies no bucket space
    and is rebuilt from (shape, dtype) alone at unflatten time.
    """

    bucket: int
    offset: int  # element offset within the bucket
    size: int    # element count (product of shape)
    shape: Tuple[int, ...]
    dtype: str   # numpy dtype name — hashable/pickleable plan metadata


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """Static flattening recipe for one pytree structure.

    Built once per (tree structure, bucket_bytes, shards) at trace time;
    `flatten_buckets`/`unflatten_buckets` are pure array reshuffles driven
    by this metadata, so the round-trip is exact by construction.
    """

    treedef: Any
    slots: Tuple[LeafSlot, ...]
    bucket_sizes: Tuple[int, ...]   # padded element counts, per bucket
    bucket_dtypes: Tuple[str, ...]  # one dtype per bucket (grouped fill)
    shards: int                     # every bucket_size is a multiple of this

    @property
    def n_buckets(self) -> int:
        return len(self.bucket_sizes)


def _ceil_to(n: int, k: int) -> int:
    return k * ((n + k - 1) // k)


def plan_buckets(tree: Any, bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 shards: int = 1) -> BucketPlan:
    """Greedy fixed-byte bucket assignment for a pytree's leaves.

    Leaves are grouped by dtype (a bucket never mixes dtypes — the
    concatenation round-trips bit-exactly with no casts) and packed in
    flatten order into buckets of at most ``bucket_bytes`` payload; a
    single leaf larger than the budget gets a bucket of its own rather
    than being split (keeps slots contiguous; the tail bucket per dtype
    is simply short). Each bucket's element count is padded up to a
    multiple of ``shards`` so a ring reduce-scatter divides it evenly.
    """
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be > 0, got {bucket_bytes}")
    if shards <= 0:
        raise ValueError(f"shards must be > 0, got {shards}")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    slots: List[LeafSlot] = []
    sizes: List[int] = []      # unpadded fill, per open/closed bucket
    dtypes: List[str] = []
    open_bucket: dict = {}     # dtype name -> bucket index still accepting
    for leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()))
        dt = jnp.asarray(leaf).dtype
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if size == 0:
            slots.append(LeafSlot(-1, 0, 0, shape, dt.name))
            continue
        cap = max(1, bucket_bytes // dt.itemsize)
        b = open_bucket.get(dt.name)
        if b is None or sizes[b] + size > cap:
            b = len(sizes)
            sizes.append(0)
            dtypes.append(dt.name)
            # An oversized leaf fills (and closes) its own bucket.
            open_bucket[dt.name] = b if size < cap else None
        slots.append(LeafSlot(b, sizes[b], size, shape, dt.name))
        sizes[b] += size
        if sizes[b] >= cap:
            open_bucket[dt.name] = None
    return BucketPlan(
        treedef=treedef,
        slots=tuple(slots),
        bucket_sizes=tuple(_ceil_to(s, shards) for s in sizes),
        bucket_dtypes=tuple(dtypes),
        shards=shards,
    )


def flatten_buckets(tree: Any, plan: BucketPlan) -> List[jax.Array]:
    """Pack a pytree (matching the plan's structure) into its buckets."""
    leaves = jax.tree_util.tree_leaves(tree)
    if len(leaves) != len(plan.slots):
        raise ValueError(
            f"tree has {len(leaves)} leaves but plan was built for "
            f"{len(plan.slots)}"
        )
    parts: List[List[jax.Array]] = [[] for _ in plan.bucket_sizes]
    fill = [0] * plan.n_buckets
    for leaf, slot in zip(leaves, plan.slots):
        if slot.bucket < 0:
            continue
        parts[slot.bucket].append(jnp.ravel(jnp.asarray(leaf)))
        fill[slot.bucket] += slot.size
    out: List[jax.Array] = []
    for b, chunks in enumerate(parts):
        pad = plan.bucket_sizes[b] - fill[b]
        if pad:
            chunks = chunks + [jnp.zeros((pad,), plan.bucket_dtypes[b])]
        out.append(chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks))
    return out


def unflatten_buckets(buckets: Sequence[jax.Array], plan: BucketPlan) -> Any:
    """Exact inverse of `flatten_buckets` (padding discarded)."""
    if len(buckets) != plan.n_buckets:
        raise ValueError(
            f"{len(buckets)} buckets given, plan has {plan.n_buckets}"
        )
    leaves = []
    for slot in plan.slots:
        if slot.bucket < 0:
            leaves.append(jnp.zeros(slot.shape, slot.dtype))
            continue
        flat = lax.slice(buckets[slot.bucket], (slot.offset,),
                         (slot.offset + slot.size,))
        leaves.append(flat.reshape(slot.shape).astype(slot.dtype))
    return jax.tree_util.tree_unflatten(plan.treedef, leaves)


# --------------------------------------------------------------------------
# Ring collectives (call inside shard_map over the named axis)
# --------------------------------------------------------------------------


def _wire(x_dtype, wire_dtype):
    """Resolve the on-wire dtype: only floats compress, and casting to the
    native dtype is a no-op we skip entirely."""
    if wire_dtype is None or not jnp.issubdtype(x_dtype, jnp.floating):
        return None
    w = jnp.dtype(wire_dtype)
    return None if w == jnp.dtype(x_dtype) else w


def _acc(x_dtype):
    """Accumulation dtype: f32 master precision for floats (the wire may
    be bf16; sums never are), native dtype for exact integer addition."""
    return jnp.float32 if jnp.issubdtype(x_dtype, jnp.floating) else x_dtype


def ring_reduce_scatter(x: jax.Array, axis_name: str, axis_size: int,
                        wire_dtype=None) -> jax.Array:
    """Ring reduce-scatter of a 1-D buffer: device ``d`` returns the fully
    summed chunk ``d`` of ``x.reshape(axis_size, -1)``.

    n−1 `ppermute` hops, each carrying 1/n of the payload: at step s a
    device forwards the partial sum for chunk (idx−s−1) mod n and adds its
    local copy of the chunk arriving next — after n−1 hops it holds the
    complete sum of exactly one chunk. `axis_size` is an explicit argument
    (not read back from the axis) so the chunking is static at trace time.
    """
    n = axis_size
    if x.ndim != 1:
        raise ValueError(f"expected a 1-D bucket, got shape {x.shape}")
    if x.shape[0] % n:
        raise ValueError(
            f"bucket of {x.shape[0]} elements does not divide over "
            f"{n} shards (plan_buckets pads for this)"
        )
    acc = _acc(x.dtype)
    chunks = x.reshape(n, -1).astype(acc)
    if n == 1:
        return chunks[0].astype(x.dtype)
    wire = _wire(x.dtype, wire_dtype)
    send_cast = (lambda v: v.astype(wire)) if wire is not None else (lambda v: v)
    perm = [(i, (i + 1) % n) for i in range(n)]
    idx = lax.axis_index(axis_name)
    send = jnp.take(chunks, (idx - 1) % n, axis=0)
    for s in range(n - 1):
        recvd = lax.ppermute(send_cast(send), axis_name, perm).astype(acc)
        send = recvd + jnp.take(chunks, (idx - s - 2) % n, axis=0)
    return send.astype(x.dtype)  # the fully-reduced chunk `idx`


def ring_all_gather(shard: jax.Array, axis_name: str, axis_size: int,
                    wire_dtype=None) -> jax.Array:
    """Ring all-gather: device ``d`` contributes chunk ``d``; every device
    returns the concatenation of all chunks (n−1 forwarding hops)."""
    n = axis_size
    if n == 1:
        return shard
    wire = _wire(shard.dtype, wire_dtype)
    send_cast = (lambda v: v.astype(wire)) if wire is not None else (lambda v: v)
    perm = [(i, (i + 1) % n) for i in range(n)]
    idx = lax.axis_index(axis_name)
    out = jnp.zeros((n,) + shard.shape, shard.dtype).at[idx].set(shard)
    send = shard
    for s in range(n - 1):
        recvd = lax.ppermute(send_cast(send), axis_name, perm).astype(shard.dtype)
        out = out.at[(idx - s - 1) % n].set(recvd)
        send = recvd
    return out.reshape((n * shard.shape[0],) + shard.shape[1:])


def ring_all_reduce(x: jax.Array, axis_name: str, axis_size: int,
                    wire_dtype=None) -> jax.Array:
    """Bandwidth-optimal allreduce of a 1-D buffer: reduce-scatter then
    all-gather — 2(n−1)/n of the payload per device on the wire vs the
    naive n× (docs/collectives.md)."""
    shard = ring_reduce_scatter(x, axis_name, axis_size, wire_dtype)
    return ring_all_gather(shard, axis_name, axis_size, wire_dtype)


# --------------------------------------------------------------------------
# Hierarchical (two-level) collectives over a (host, device) mesh
# --------------------------------------------------------------------------
#
# The multi-host decomposition of arXiv:1810.11112: ring each mesh axis
# separately instead of one flat ring over every device. The intra-host
# ring moves (n_dev−1)/n_dev of the bucket over fast ICI; the inter-host
# exchange then rings only the 1/n_dev-sized chunks over the slow links —
# (n_host−1)/(n_host·n_dev) of the bucket per device on DCN, vs a flat
# global ring's (N−1)/N of it. docs/collectives.md has the cost model.
#
# Shard indexing: after hier_reduce_scatter, device (h, d) holds the fully
# reduced row ``d*n_host + h`` of ``x.reshape(n_host*n_dev, -1)`` — chunk d
# from the device-axis ring, sub-chunk h from the host-axis ring.
# hier_all_gather inverts exactly that placement, and hier_shard_rows /
# hier_unshard_rows lay a bucket out as (n_host*n_dev, L) rows in
# shard_map's P((host, data)) row order so ZeRO-3 resident shards line up
# with what the rings deliver.


def hier_reduce_scatter(x: jax.Array, host_axis: str, n_host: int,
                        dev_axis: str, n_dev: int,
                        wire_dtype=None) -> jax.Array:
    """Two-level reduce-scatter: intra-host ring RS over the device axis,
    then the inter-host shard exchange — a ring RS of the surviving chunk
    over the host axis. Device (h, d) returns the globally summed row
    ``d*n_host + h`` of ``x.reshape(n_host*n_dev, -1)``."""
    local = ring_reduce_scatter(x, dev_axis, n_dev, wire_dtype)
    return ring_reduce_scatter(local, host_axis, n_host, wire_dtype)


def hier_all_gather(shard: jax.Array, host_axis: str, n_host: int,
                    dev_axis: str, n_dev: int, wire_dtype=None) -> jax.Array:
    """Exact inverse of `hier_reduce_scatter`: all-gather over the host
    axis rebuilds each device's chunk, then the intra-host all-gather
    rebuilds the full bucket."""
    chunk = ring_all_gather(shard, host_axis, n_host, wire_dtype)
    return ring_all_gather(chunk, dev_axis, n_dev, wire_dtype)


def hier_all_reduce(x: jax.Array, host_axis: str, n_host: int,
                    dev_axis: str, n_dev: int, wire_dtype=None) -> jax.Array:
    """Hierarchical allreduce of a 1-D bucket (RS then AG, per level)."""
    shard = hier_reduce_scatter(x, host_axis, n_host, dev_axis, n_dev,
                                wire_dtype)
    return hier_all_gather(shard, host_axis, n_host, dev_axis, n_dev,
                           wire_dtype)


def hier_shard_rows(bucket: jax.Array, n_host: int, n_dev: int) -> jax.Array:
    """Lay a 1-D bucket out as (n_host*n_dev, L) resident-shard rows in
    shard_map's P((host, data)) row order: row ``h*n_dev + d`` carries the
    sub-chunk the hierarchical rings place on device (h, d) — i.e. row
    ``d*n_host + h`` of the natural reshape. With n_host=1 this is just
    ``bucket.reshape(n_dev, -1)`` (the flat-ring layout)."""
    if bucket.shape[0] % (n_host * n_dev):
        raise ValueError(
            f"bucket of {bucket.shape[0]} elements does not divide over "
            f"{n_host}x{n_dev} shards"
        )
    if n_host == 1:
        return bucket.reshape(n_dev, -1)
    return (bucket.reshape(n_dev, n_host, -1)
            .transpose(1, 0, 2)
            .reshape(n_host * n_dev, -1))


def hier_unshard_rows(rows: jax.Array, n_host: int, n_dev: int) -> jax.Array:
    """Exact inverse of `hier_shard_rows`: rows back to the 1-D bucket."""
    if n_host == 1:
        return rows.reshape(-1)
    return (rows.reshape(n_host, n_dev, -1)
            .transpose(1, 0, 2)
            .reshape(-1))


# --------------------------------------------------------------------------
# Tree-level API (what the trainers call)
# --------------------------------------------------------------------------


def wire_dtype_arg(comm) -> Optional[str]:
    """The wire_dtype argument the ring primitives expect, from a
    config.CommConfig ("float32" means no compression → None)."""
    if comm is None or comm.wire_dtype in (None, "float32"):
        return None
    return comm.wire_dtype


def tree_all_reduce(tree: Any, axis_name: str, axis_size: int,
                    comm=None, *, host_axis: Optional[str] = None,
                    host_size: int = 1) -> Any:
    """SUM-allreduce a pytree over the batch-parallel axes, per the comm
    config.

    comm=None or impl="psum": one monolithic `lax.psum` (the historical
    behavior — XLA picks the algorithm; on a hierarchical mesh it reduces
    over both axes at once). impl="ring": the pytree is bucketed
    (comm.bucket_bytes) and each bucket goes through the explicit ring,
    optionally bf16-on-the-wire. impl="hierarchical": each bucket goes
    through the two-level (host, device) ring; callers pass the host axis
    name/size alongside the device axis. Call inside shard_map; ring and
    hierarchical callers must build the enclosing shard_map with the
    replication checker off (mesh.shard_map(check_vma=False)) — ppermute
    outputs are per-device values the checker cannot prove replicated,
    even though RS+AG leaves every device with identical sums.
    """
    if comm is None or comm.impl == "psum":
        axes = (host_axis, axis_name) if host_axis is not None else axis_name
        return lax.psum(tree, axes)
    wire = wire_dtype_arg(comm)
    if comm.impl == "hierarchical":
        if host_axis is None:
            raise ValueError(
                "impl='hierarchical' needs a (host, device) mesh — pass "
                "host_axis/host_size (mesh.make_hier_mesh builds the mesh)"
            )
        plan = plan_buckets(tree, comm.bucket_bytes,
                            shards=host_size * axis_size)
        buckets = [
            hier_all_reduce(b, host_axis, host_size, axis_name, axis_size,
                            wire)
            for b in flatten_buckets(tree, plan)
        ]
        return unflatten_buckets(buckets, plan)
    if comm.impl != "ring":
        raise ValueError(f"unknown comm impl {comm.impl!r}")
    plan = plan_buckets(tree, comm.bucket_bytes, shards=axis_size)
    buckets = [
        ring_all_reduce(b, axis_name, axis_size, wire)
        for b in flatten_buckets(tree, plan)
    ]
    return unflatten_buckets(buckets, plan)


def reduce_scatter_buckets(buckets: Sequence[jax.Array], axis_name: str,
                           axis_size: int, wire_dtype=None, *,
                           host_axis: Optional[str] = None,
                           host_size: int = 1) -> List[jax.Array]:
    """Reduce-scatter each bucket → per-device shard list. The overlap
    building block: train/zoo.py calls this per microbatch (the shards
    accumulate sharded, 1/n the memory of full grads) and defers the
    single `all_gather_buckets` to after the last microbatch. With a
    host axis the two-level hierarchical ring runs instead of the flat
    one (buckets must be planned with shards=host_size*axis_size)."""
    if host_axis is not None:
        return [
            hier_reduce_scatter(b, host_axis, host_size, axis_name,
                                axis_size, wire_dtype)
            for b in buckets
        ]
    return [
        ring_reduce_scatter(b, axis_name, axis_size, wire_dtype)
        for b in buckets
    ]


def all_gather_buckets(shards: Sequence[jax.Array], axis_name: str,
                       axis_size: int, wire_dtype=None, *,
                       host_axis: Optional[str] = None,
                       host_size: int = 1) -> List[jax.Array]:
    """Inverse of `reduce_scatter_buckets`: rematerialize full buckets."""
    if host_axis is not None:
        return [
            hier_all_gather(s, host_axis, host_size, axis_name, axis_size,
                            wire_dtype)
            for s in shards
        ]
    return [
        ring_all_gather(s, axis_name, axis_size, wire_dtype)
        for s in shards
    ]
