"""ExecutionPlan: one declarative, serializable execution contract.

Everything that decides HOW a run executes — mesh topology and axis
sizes ``(host, stage, data, model)``, the collective implementation ×
bucket × wire × overlap, the ZeRO level, pipeline stages/split, the
fused-step pieces, gradient accumulation, activation dtypes, sharding
policy, and the serve-side compile/AOT policy — lives in ONE frozen
dataclass with ONE resolution site (:func:`build_plan`), one legality
matrix (:meth:`ExecutionPlan.validate`), one mesh constructor
(:meth:`ExecutionPlan.make_mesh` — the only mesh-construction site in
the package outside ``parallel/mesh.py``), and a schema-versioned JSON
round-trip (``plan.json``, written by ``tune --report``, loaded by
``--plan``/``PCNN_PLAN``).

Per-knob **provenance** records where each resolved value came from —
``flag`` beats ``env`` beats ``autotune`` beats ``default`` — so
``plan show`` can answer "why is this run using a ring collective"
without re-deriving the config layering.  Provenance is carried on the
plan but excluded from equality and from the content fingerprint: two
plans that execute identically ARE identical, however their knobs were
sourced.

The **fingerprint** (sha256 of the canonical field JSON, 16 hex chars)
is the plan's stable identity: it is stamped into checkpoint metadata
(restore refuses a mismatched file unless ``--replan``), folded into
the serve engine's on-disk AOT-executable cache key, and used by the
elastic runtime's recompile-once gate (``derive_resized`` returning an
already-seen plan means the jitted step can be reused).

Import-light on purpose: no jax at module scope — building, validating,
serializing, and diffing plans must work in a process that never
initializes a backend (``plan show``, ``check --plan``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, Optional, Tuple

PLAN_SCHEMA_VERSION = 1

#: Precedence order for per-knob provenance (highest first).
PROVENANCE_ORDER = ("flag", "env", "autotune", "default")


class PlanError(ValueError):
    """Base class for every typed plan failure."""


class PlanSchemaError(PlanError):
    """A plan file could not be decoded: wrong schema version, unknown
    fields, or a stored fingerprint that does not match the stored
    fields (tamper/corruption)."""


class PlanLegalityError(PlanError):
    """The knob combination is outside the legality matrix (the checks
    that used to live as ad-hoc ``cli.py`` argument guards)."""


class PlanMismatchError(PlanError):
    """A checkpoint was written under a different ExecutionPlan than the
    one live in this run.  Carries both fingerprints; pass ``--replan``
    (or go through the elastic reshard path, which recomputes sharding)
    to load it anyway."""

    def __init__(self, *, stored: str, live: str, path: str = ""):
        self.stored = stored
        self.live = live
        self.path = path
        where = f" in {path}" if path else ""
        super().__init__(
            f"checkpoint plan fingerprint {stored}{where} does not match "
            f"the live plan {live}; the file was written under a different "
            "execution contract — rerun with the original knobs, or pass "
            "--replan to re-shard it under the live plan"
        )


#: The single error text for "this mode owns the mesh axes" — the three
#: near-identical strings cli.py used to carry, now one constant.
MESH_AXES_OWNED_ERROR = (
    "{owner} builds its own {axes} mesh over all devices; "
    "drop --mesh-data/--mesh-model{extra}"
)

#: Explicit-collective path without a mesh (the old cli.py guard text).
COMM_NEEDS_MESH_ERROR = (
    "--comm-impl/PCNN_COMM_* select the explicit mesh collective path; "
    "add --mesh-data N (or --mesh-model)"
)

COMM_DATA_ONLY_ERROR = (
    "--comm-impl is data-parallel only; the explicit collective path "
    "composes with the data axis, not --mesh-model (drop one of the two)"
)


@dataclasses.dataclass(frozen=True)
class ExecutionPlan:
    """The full execution contract, resolved and frozen.

    Field semantics (every default is the historical single-device
    GSPMD path — a default-constructed plan changes nothing):

    - ``hosts``/``stages``/``data``/``model``: the 4-axis mesh topology.
      ``data=None`` with ``model=1``, ``stages=1`` and no hierarchical
      comm means *no mesh* (:meth:`make_mesh` returns None).  For
      pipeline and hierarchical modes the mode owns the axis sizes and
      ``data`` stays None ("all remaining devices").
    - ``comm_impl``: None = compiler-inserted GSPMD psum; "psum"/"ring"/
      "hierarchical" = the explicit collective path with ``bucket_bytes``
      × ``wire_dtype`` × ``overlap``.
    - ``zero``: optimizer-state partitioning level (0, 2, 3); non-zero
      requires the fused update-on-arrival step (``fused_update``).
    - ``fused``/``fused_update``/``fused_tail``/``act_dtype``: the
      round-7 fused-step pieces (``fused`` = a FusedStepConfig exists).
    - ``accum``: gradient-accumulation microbatch count.
    - ``split``/``pipe_wire_dtype``/``pipe_act_dtype``: pipeline stage
      boundaries and wire/compute dtypes (meaningful when stages > 1).
    - ``param_sharding``/``opt_sharding``: per-leaf sharding policy the
      trainer applies ("replicated", "model" = filter/channel sharding
      over the model axis, "zero3" = resident shard rows over data).
      The actual per-leaf PartitionSpecs derive from these policies
      (parallel/zoo_sharding.py PARAM_SPECS, zoo.init_zero3_state).
    - ``precompile``/``aot_cache``: the serve-side compile policy — AOT
      every bucket eagerly, and persist executables on disk keyed by
      this plan's fingerprint.
    - ``elastic``: True on plans produced by :func:`derive_resized` —
      the mesh is built over the surviving-device prefix
      (``make_elastic_mesh``) instead of the full device set.
    """

    hosts: Optional[int] = None
    stages: int = 1
    data: Optional[int] = None
    model: int = 1
    comm_impl: Optional[str] = None
    bucket_bytes: int = 4 * 1024 * 1024
    wire_dtype: str = "float32"
    overlap: bool = True
    zero: int = 0
    fused: bool = False
    fused_update: bool = False
    fused_tail: bool = True
    act_dtype: str = "float32"
    accum: int = 1
    # pipelined=True with stages=1 is the DEGENERATE pipeline (a real
    # (stage=1, data) mesh + the 1F1B machinery delegating to the flat
    # ring step, bit-exact by construction) — distinct from the default
    # non-pipelined stages=1.
    pipelined: bool = False
    split: str = ""
    pipe_wire_dtype: str = "float32"
    pipe_act_dtype: str = "float32"
    param_sharding: str = "replicated"
    opt_sharding: str = "replicated"
    precompile: bool = False
    aot_cache: bool = False
    elastic: bool = False
    provenance: Tuple[Tuple[str, str], ...] = dataclasses.field(
        default=(), compare=False
    )

    # -- identity --------------------------------------------------------

    def fields(self) -> Dict[str, Any]:
        """Identity fields as a plain dict (provenance excluded)."""
        d = dataclasses.asdict(self)
        d.pop("provenance")
        return d

    def fingerprint(self) -> str:
        """Stable 16-hex-char content hash of the identity fields.

        Line of trust: everything downstream that must never silently
        cross plans — checkpoint restore, the AOT executable cache, the
        elastic recompile gate — compares THIS string."""
        blob = json.dumps(self.fields(), sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def __hash__(self) -> int:  # frozen dataclass + unhashable-safe use
        return hash(self.fingerprint())

    def provenance_of(self, field_name: str) -> str:
        for name, source in self.provenance:
            if name == field_name:
                return source
        return "default"

    # -- serialization ---------------------------------------------------

    def to_json_dict(self) -> Dict[str, Any]:
        return {
            "version": PLAN_SCHEMA_VERSION,
            "fingerprint": self.fingerprint(),
            "plan": self.fields(),
            "provenance": dict(self.provenance),
        }

    def to_json(self) -> str:
        """Byte-stable JSON: sorted keys, fixed indent, trailing newline
        — save(load(s)) reproduces s exactly."""
        return json.dumps(self.to_json_dict(), sort_keys=True, indent=2) + "\n"

    @classmethod
    def from_json_dict(cls, doc: Dict[str, Any]) -> "ExecutionPlan":
        version = doc.get("version")
        if version != PLAN_SCHEMA_VERSION:
            raise PlanSchemaError(
                f"plan schema version {version!r} is not the supported "
                f"version {PLAN_SCHEMA_VERSION}; regenerate the file with "
                "this build's `tune --report` (or `plan show --save`)"
            )
        raw = doc.get("plan")
        if not isinstance(raw, dict):
            raise PlanSchemaError("plan file has no 'plan' object")
        known = {f.name for f in dataclasses.fields(cls)} - {"provenance"}
        unknown = sorted(set(raw) - known)
        if unknown:
            raise PlanSchemaError(
                f"plan file carries unknown field(s) {unknown} — written "
                "by a newer build? (schema version is "
                f"{PLAN_SCHEMA_VERSION} either way; refusing to guess)"
            )
        prov = doc.get("provenance", {})
        if not isinstance(prov, dict):
            raise PlanSchemaError("plan 'provenance' must be an object")
        plan = cls(**raw, provenance=tuple(sorted(prov.items())))
        stored = doc.get("fingerprint")
        if stored is not None and stored != plan.fingerprint():
            raise PlanSchemaError(
                f"stored fingerprint {stored} does not match the stored "
                f"fields (recomputed {plan.fingerprint()}) — the file was "
                "hand-edited or torn; regenerate it"
            )
        return plan

    # -- mesh ------------------------------------------------------------

    def make_mesh(self, devices=None):
        """Build THE mesh this plan describes, or None for the
        single-device/GSPMD path.  This is the one mesh-construction
        site outside ``parallel/mesh.py`` (the ``mesh-outside-plan``
        graftcheck rule pins that); jax is imported lazily so plan
        manipulation never initializes a backend."""
        from parallel_cnn_tpu.config import MeshConfig
        from parallel_cnn_tpu.parallel import mesh as mesh_lib

        if self.elastic:
            return mesh_lib.make_elastic_mesh(
                self.world(), n_hosts=self.hosts or 1, devices=devices
            )
        if self.pipelined or self.stages > 1:
            return mesh_lib.make_pipeline_mesh(self.stages, devices=devices)
        if self.comm_impl == "hierarchical":
            return mesh_lib.make_hier_mesh(n_hosts=self.hosts,
                                           devices=devices)
        if self.data is not None or self.model > 1:
            return mesh_lib.make_mesh(
                MeshConfig(data=self.data, model=self.model), devices=devices
            )
        return None

    def world(self) -> int:
        """Device count the plan claims, when its axes pin one (elastic
        derived plans always do)."""
        if self.data is None:
            raise PlanError("plan does not pin a world size (data=None)")
        return (self.hosts or 1) * self.data * max(self.stages, 1) \
            * max(self.model, 1)

    # -- legality --------------------------------------------------------

    def validate(self) -> "ExecutionPlan":
        """The legality matrix, with typed errors.  These checks used to
        live as argument guards in cli.py; every consumer (CLI, plan
        files, tune hand-off, elastic derivation) now passes through the
        same matrix.  Returns self so call sites can chain."""
        if self.comm_impl not in (None, "psum", "ring", "hierarchical"):
            raise PlanLegalityError(
                f"unknown comm impl {self.comm_impl!r} "
                "(psum, ring, or hierarchical)"
            )
        explicit_axes = self.data is not None or self.model > 1
        if self.pipelined or self.stages > 1:
            if explicit_axes and not self.elastic:
                raise PlanLegalityError(MESH_AXES_OWNED_ERROR.format(
                    owner="--pipeline-stages", axes="(stage, data)",
                    extra="",
                ))
            if self.comm_impl == "hierarchical":
                raise PlanLegalityError(
                    "pipeline gradients reduce over the flat data axis; "
                    "use --comm-impl ring (not hierarchical)"
                )
            if self.zero == 3 and self.stages > 1:
                raise PlanLegalityError(
                    "pipeline composes with ZeRO-2 only: ZeRO-3's "
                    "just-in-time head gathers contradict per-stage param "
                    "residency (docs/pipeline.md)"
                )
        elif self.comm_impl == "hierarchical":
            if explicit_axes and not self.elastic:
                raise PlanLegalityError(MESH_AXES_OWNED_ERROR.format(
                    owner="--comm-impl hierarchical", axes="(host, device)",
                    extra=" (size the host axis with --comm-hosts)",
                ))
            if self.hosts is not None and self.hosts < 2 and not self.elastic:
                raise PlanLegalityError(
                    f"hierarchical comm needs a host axis of >= 2 "
                    f"(got hosts={self.hosts}); use --comm-impl ring on "
                    "a single host"
                )
        if self.comm_impl is not None and not self.elastic:
            mesh_present = (explicit_axes or self.pipelined
                            or self.stages > 1
                            or self.comm_impl == "hierarchical")
            if not mesh_present:
                raise PlanLegalityError(COMM_NEEDS_MESH_ERROR)
            if self.model > 1:
                raise PlanLegalityError(COMM_DATA_ONLY_ERROR)
        if self.zero not in (0, 2, 3):
            raise PlanLegalityError(f"zero level {self.zero} not in (0, 2, 3)")
        if self.zero > 0 and not self.fused_update:
            raise PlanLegalityError(
                f"zero={self.zero} shards optimizer state into the fused "
                "update-on-arrival collective schedule; it requires the "
                "fused step (fused ⟺ zero>0)"
            )
        if self.fused_update and self.zero not in (2, 3):
            raise PlanLegalityError(
                "fused update-on-arrival partitions optimizer state; "
                f"zero must be 2 or 3 (got {self.zero})"
            )
        if self.zero == 2 and self.comm_impl != "ring":
            raise PlanLegalityError(
                "ZeRO-2 update-on-arrival rides the flat ring; use "
                "--comm-impl ring (or zero=3 on a hierarchical mesh)"
            )
        if self.zero == 3 and self.comm_impl not in ("ring", "hierarchical"):
            raise PlanLegalityError(
                "ZeRO-3 needs the explicit ring or hierarchical collective "
                "path (--comm-impl ring|hierarchical)"
            )
        if self.fused_update and not self.fused:
            raise PlanLegalityError("fused_update implies fused")
        if self.accum < 1:
            raise PlanLegalityError(f"accum must be >= 1, got {self.accum}")
        if self.param_sharding not in ("replicated", "model", "zero3"):
            raise PlanLegalityError(
                f"unknown param sharding policy {self.param_sharding!r}"
            )
        if self.param_sharding == "model" and self.model <= 1:
            raise PlanLegalityError(
                "param_sharding='model' needs a model axis > 1"
            )
        return self

    # -- config views ----------------------------------------------------

    def comm_config(self):
        """The CommConfig this plan implies, or None (GSPMD path)."""
        if self.comm_impl is None:
            return None
        from parallel_cnn_tpu.config import CommConfig

        return CommConfig(
            impl=self.comm_impl, bucket_bytes=self.bucket_bytes,
            wire_dtype=self.wire_dtype, overlap=self.overlap,
            hosts=self.hosts,
        )

    def fused_config(self):
        """The FusedStepConfig this plan implies, or None."""
        if not self.fused:
            return None
        from parallel_cnn_tpu.config import FusedStepConfig

        return FusedStepConfig(
            update=self.fused_update, tail=self.fused_tail,
            act_dtype=self.act_dtype,
            zero=self.zero if self.zero in (2, 3) else 2,
        )

    def pipeline_config(self):
        """The PipelineConfig this plan implies, or None."""
        if not self.pipelined and self.stages <= 1:
            return None
        from parallel_cnn_tpu.config import PipelineConfig

        return PipelineConfig(
            stages=self.stages, split=self.split,
            wire_dtype=self.pipe_wire_dtype, act_dtype=self.pipe_act_dtype,
        )

    # -- cost-table mapping ----------------------------------------------

    def cost_table_key(self) -> Tuple[str, Optional[str]]:
        """(graftcheck cost-table entry, closed-form collective kind)
        this plan's step is ratcheted under — what lets ``check --plan``
        verify a plan file against the shipped cost baseline without
        running it.  The kind is None when the plan has no explicit
        collective (psum/GSPMD: nothing to count against a closed form).
        """
        if self.stages > 1:
            return (f"train.pipeline_step.pipe{self.stages}_ring",
                    "pipeline_ring")
        if self.zero == 3:
            if self.comm_impl == "hierarchical":
                return ("zoo.zero3_step.hier_bf16", "zero3_hier")
            return ("zoo.zero3_step.ring_bf16", "zero3_ring")
        if self.zero == 2:
            return ("zoo.fused_step.ring_bf16", "zero2_ring")
        if self.comm_impl == "hierarchical":
            return ("zoo.comm_step.hier_bf16",
                    "hier_overlap" if self.overlap else "hier_post")
        if self.comm_impl == "ring":
            return ("zoo.comm_step.ring_bf16",
                    "ring_overlap" if self.overlap else "ring_post")
        return ("plan.resolved", None)


# ---------------------------------------------------------------------------
# Resolution: Config (+argparse namespace) -> ExecutionPlan with provenance
# ---------------------------------------------------------------------------

#: plan field -> (argparse attribute, env var) for provenance labeling.
#: None means "no flag/env source exists for this knob".
_KNOB_SOURCES: Dict[str, Tuple[Optional[str], Optional[str]]] = {
    "hosts": ("comm_hosts", "PCNN_COMM_HOSTS"),
    "stages": ("pipeline_stages", "PCNN_PIPELINE_STAGES"),
    "data": ("mesh_data", None),
    "model": ("mesh_model", None),
    "comm_impl": ("comm_impl", "PCNN_COMM_IMPL"),
    "bucket_bytes": ("comm_bucket_mb", "PCNN_COMM_BUCKET_BYTES"),
    "wire_dtype": ("comm_wire_dtype", "PCNN_COMM_WIRE_DTYPE"),
    "overlap": (None, "PCNN_COMM_OVERLAP"),
    "zero": (None, "PCNN_ZERO_LEVEL"),
    "fused": ("fused_step", "PCNN_FUSED_STEP"),
    "fused_update": ("fused_step", "PCNN_FUSED_STEP"),
    "act_dtype": ("act_dtype", "PCNN_ACT_DTYPE"),
    "accum": ("accum_steps", None),
    "pipelined": ("pipeline_stages", "PCNN_PIPELINE_STAGES"),
    "split": ("pipeline_split", "PCNN_PIPELINE_SPLIT"),
    "pipe_wire_dtype": ("pipeline_wire_dtype", "PCNN_PIPELINE_WIRE_DTYPE"),
    "pipe_act_dtype": ("pipeline_act_dtype", "PCNN_PIPELINE_ACT_DTYPE"),
    "precompile": ("no_precompile", "PCNN_SERVE_PRECOMPILE"),
    "aot_cache": ("aot_cache_dir", "PCNN_SERVE_AOT_CACHE_DIR"),
}

def _provenance(
    field_name: str, args, present_env: frozenset, autotune_filled
) -> str:
    """flag > env > autotune > default, per knob.

    The autotune check runs first NOT because autotune outranks flags —
    cli.config_from_args records a knob in ``_autotune_filled`` only
    when neither a flag nor an env var pinned it (and then writes the
    tuned value back onto ``args``, which would otherwise read as a
    flag here); membership is therefore proof the higher layers passed.
    """
    if field_name in autotune_filled:
        return "autotune"
    flag_attr, env_var = _KNOB_SOURCES.get(field_name, (None, None))
    flag_val = getattr(args, flag_attr, None) if flag_attr and args else None
    # store_true flags default to False, value flags to None — either
    # sentinel means "not passed on the command line".
    if flag_val is not None and flag_val is not False:
        return "flag"
    if env_var is not None and env_var in present_env:
        return "env"
    return "default"


def build_plan(config, args=None, *, autotune_filled=()) -> "ExecutionPlan":
    """THE resolution site: a layered Config (flags already applied over
    env over autotune over defaults by ``cli.config_from_args``) becomes
    one ExecutionPlan, with per-knob provenance labels.

    ``args`` is the argparse namespace (None for programmatic callers —
    provenance then degrades to env/autotune/default).
    ``autotune_filled`` names the knobs the autotune block filled in
    (cli records them; a knob is labeled "autotune" only when neither a
    flag nor an env var pinned it).
    """
    from parallel_cnn_tpu import config as config_mod

    comm = getattr(config, "comm", None)
    fused = getattr(config, "fused", None)
    pipeline = getattr(config, "pipeline", None)
    mesh_cfg = getattr(config, "mesh", None)
    serve = getattr(config, "serve", None)
    net = getattr(config, "net", None)

    values: Dict[str, Any] = {}
    if comm is not None:
        values.update(
            comm_impl=comm.impl, bucket_bytes=comm.bucket_bytes,
            wire_dtype=comm.wire_dtype, overlap=comm.overlap,
            hosts=comm.hosts,
        )
    if fused is not None:
        values.update(
            fused=True, fused_update=fused.update, fused_tail=fused.tail,
            act_dtype=fused.act_dtype,
            zero=fused.zero if fused.update else 0,
        )
    if pipeline is not None:
        values.update(
            pipelined=True,
            stages=pipeline.stages, split=pipeline.split,
            pipe_wire_dtype=pipeline.wire_dtype,
            pipe_act_dtype=pipeline.act_dtype,
        )
    if mesh_cfg is not None:
        values.update(data=mesh_cfg.data, model=mesh_cfg.model)
    if args is not None and getattr(args, "accum_steps", None):
        values["accum"] = args.accum_steps
    if serve is not None:
        values["precompile"] = serve.precompile
    if net is not None:
        values["aot_cache"] = net.aot_cache_dir is not None
    # Sharding policy follows the partitioning mode deterministically.
    if values.get("zero", 0) == 3:
        values["param_sharding"] = "zero3"
        values["opt_sharding"] = "zero3"
    elif values.get("model", 1) > 1:
        values["param_sharding"] = "model"
        values["opt_sharding"] = "model"
    elif values.get("zero", 0) == 2:
        values["opt_sharding"] = "zero3"  # ZeRO-2: opt shards, params full

    present_env = config_mod.present_plan_env()
    filled = frozenset(autotune_filled) | frozenset(
        getattr(args, "_autotune_filled", ()) if args is not None else ()
    )
    prov = tuple(sorted(
        (name, _provenance(name, args, present_env, filled))
        for name in values
    ))
    return ExecutionPlan(**values, provenance=prov)


def serve_plan(serve_cfg, net_cfg=None, *,
               cache_dir: Optional[str] = None) -> "ExecutionPlan":
    """The serving front door's plan: eval sharding is single-device
    replicated, so only the compile/AOT policy varies.  Its fingerprint
    folds into the engines' on-disk AOT-executable cache key
    (serve/engine.py) — executables compiled under one plan never serve
    another."""
    return ExecutionPlan(
        precompile=bool(getattr(serve_cfg, "precompile", False)),
        aot_cache=bool(
            cache_dir
            or (net_cfg is not None
                and getattr(net_cfg, "aot_cache_dir", None))
        ),
        provenance=(("aot_cache", "flag"), ("precompile", "flag")),
    )


# ---------------------------------------------------------------------------
# Elastic derivation
# ---------------------------------------------------------------------------

def derive_resized(
    plan: ExecutionPlan, new_world: int, *, n_hosts: Optional[int] = None
) -> ExecutionPlan:
    """The plan an elastic resize lands on: same contract, new topology.

    Mirrors ``mesh.make_elastic_mesh``'s topology decision exactly —
    hierarchical while the host axis still divides the new world, flat
    ring otherwise — so the derived plan's fields stay truthful about
    the mesh :meth:`ExecutionPlan.make_mesh` will build.  Deriving is
    pure and deterministic: resizing back to an already-seen world
    yields an EQUAL plan (same fingerprint), which is what gates the
    trainer's recompile-once step cache.
    """
    if new_world < 1:
        raise PlanLegalityError(f"world must be >= 1, got {new_world}")
    if n_hosts is None:
        h = plan.hosts or 1
        n_hosts = h if h > 1 and new_world % h == 0 else 1
    if n_hosts > 1 and new_world % n_hosts != 0:
        raise PlanLegalityError(
            f"elastic world {new_world} is not divisible by "
            f"n_hosts {n_hosts}"
        )
    hier = n_hosts > 1
    prov = dict(plan.provenance)
    for name in ("hosts", "data", "comm_impl"):
        prov[name] = "elastic"
    return dataclasses.replace(
        plan,
        hosts=n_hosts if hier else None,
        data=new_world // n_hosts,
        comm_impl="hierarchical" if hier else "ring",
        elastic=True,
        provenance=tuple(sorted(prov.items())),
    )


# ---------------------------------------------------------------------------
# Files
# ---------------------------------------------------------------------------

def save_plan(path, plan: ExecutionPlan) -> None:
    with open(path, "w") as f:
        f.write(plan.to_json())


def load_plan(path) -> ExecutionPlan:
    """Load a plan file: either a bare plan document or a ``tune
    --report`` artifact (whose chosen autotune section converts through
    the thin :class:`analysis.autotune.Plan` view) — the lossless
    tune → train hand-off."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise PlanError(f"cannot read plan file {path}: {e}") from e
    except ValueError as e:
        raise PlanSchemaError(f"plan file {path} is not JSON: {e}") from e
    if not isinstance(doc, dict):
        raise PlanSchemaError(f"plan file {path} is not a JSON object")
    inner = doc.get("plan")
    if isinstance(inner, dict) and "plan" in inner and "version" in inner:
        # A `tune --report` artifact embedding a full plan document
        # under "plan" (a bare plan doc's "plan" is the flat field map).
        return ExecutionPlan.from_json_dict(inner)
    if inner is not None or "autotune" not in doc:
        return ExecutionPlan.from_json_dict(doc)
    # tune --report artifact without an embedded plan: convert the
    # chosen autotune plan (older reports; `tune` now embeds "plan").
    from parallel_cnn_tpu.analysis import autotune as autotune_lib

    chosen, section = autotune_lib.load_chosen_plan(path)
    return chosen.to_execution_plan(
        n_host=int(section.get("n_host", 1) or 1),
        n_dev=int(section.get("n_dev", 0) or 0) or None,
    )


# ---------------------------------------------------------------------------
# Rendering: `plan show` / `plan diff`
# ---------------------------------------------------------------------------

def format_plan(plan: ExecutionPlan, *, title: str = "") -> str:
    """The resolved plan, one knob per line with provenance."""
    lines = []
    if title:
        lines.append(title)
    lines.append(f"fingerprint: {plan.fingerprint()}  "
                 f"(schema v{PLAN_SCHEMA_VERSION})")
    entry, kind = plan.cost_table_key()
    lines.append(f"cost table:  {entry}"
                 + (f"  [{kind}]" if kind else ""))
    width = max(len(f.name) for f in dataclasses.fields(ExecutionPlan))
    for name, value in sorted(plan.fields().items()):
        src = plan.provenance_of(name)
        lines.append(f"  {name:<{width}}  {value!r:<12}  [{src}]")
    return "\n".join(lines)


def diff_plans(a: ExecutionPlan, b: ExecutionPlan) -> str:
    """Field-by-field diff; empty string when the plans are equal."""
    fa, fb = a.fields(), b.fields()
    lines = []
    for name in sorted(fa):
        if fa[name] != fb[name]:
            lines.append(
                f"  {name}: {fa[name]!r} [{a.provenance_of(name)}] -> "
                f"{fb[name]!r} [{b.provenance_of(name)}]"
            )
    if not lines:
        return ""
    header = (f"plans differ ({a.fingerprint()} -> {b.fingerprint()}), "
              f"{len(lines)} field(s):")
    return "\n".join([header] + lines)
