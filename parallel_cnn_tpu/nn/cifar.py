"""The 3-conv-block CIFAR-10 CNN (BASELINE.json config #3:
"3-conv-block CNN on CIFAR-10 (32x32x3), DP over v5e-8")."""

from __future__ import annotations

from parallel_cnn_tpu.nn.core import Sequential
from parallel_cnn_tpu.nn.layers import (
    BatchNorm,
    Conv2D,
    Dense,
    Flatten,
    MaxPool,
    ReLU,
)

IN_SHAPE = (32, 32, 3)
NUM_CLASSES = 10


def cifar_cnn(num_classes: int = NUM_CLASSES) -> Sequential:
    """conv-bn-relu ×2 per block, 3 blocks (32→64→128 ch), maxpool between,
    dense head — the standard compact CIFAR baseline."""

    def block(ch):
        return [
            Conv2D(ch),
            BatchNorm(),
            ReLU(),
            Conv2D(ch),
            BatchNorm(),
            ReLU(),
            MaxPool(),
        ]

    return Sequential(
        [*block(32), *block(64), *block(128), Flatten(), Dense(num_classes)]
    )
