"""Standard layers, NHWC, TPU-first.

Convs lower to `lax.conv_general_dilated` with NHWC/HWIO dimension numbers
— channels-last keeps the channel dim on the lane axis of the MXU so XLA
tiles 8×128 without transposes. BatchNorm means are plain batch means: in
GSPMD data-parallel training (jit + batch sharded over the mesh's data
axis) XLA turns them into global cross-replica means automatically — no
explicit psum needed (contrast the reference's hand-placed MPI_Reduce per
kernel, MPI/layer.h)."""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from parallel_cnn_tpu.nn.core import Module, Shape


def _he_normal(key, shape, fan_in, dtype):
    return jax.random.normal(key, shape, dtype) * jnp.sqrt(2.0 / fan_in)


@dataclasses.dataclass(frozen=True)
class Conv2D(Module):
    """features × (kh, kw) conv, stride/padding configurable, He init.

    backend="pallas" routes supported shapes (square odd k ∈ {1,3,5,7},
    stride 1/2, SAME — every conv in the ResNet and VGG families, 7×7-s2
    stem included) through the hand-written tapped-matmul kernels in
    ops/pallas_conv.py — the zoo's native-kernel path (BASELINE.json
    config #4). Unsupported shapes raise at construction-use time rather
    than silently falling back, so a "pallas" model is what it claims
    to be.
    """

    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    use_bias: bool = True
    backend: str = "xla"

    def init(self, key, in_shape: Shape):
        h, w, c = in_shape
        kh, kw = self.kernel
        wkey, _ = jax.random.split(key)
        fan_in = kh * kw * c
        params = {
            "w": _he_normal(wkey, (kh, kw, c, self.features), fan_in, jnp.float32)
        }
        if self.use_bias:
            params["b"] = jnp.zeros((self.features,), jnp.float32)
        out = lax.conv_general_shape_tuple(
            (1, h, w, c),
            (kh, kw, c, self.features),
            self.strides,
            self.padding,
            ("NHWC", "HWIO", "NHWC"),
        )
        return params, {}, tuple(out[1:])

    def apply(self, params, state, x, train: bool = False):
        use_pallas = self.backend == "pallas"
        if use_pallas:
            from parallel_cnn_tpu.ops import pallas_conv

            if not pallas_conv.supports(self.kernel, self.strides, self.padding):
                raise ValueError(
                    f"pallas conv backend does not cover kernel={self.kernel} "
                    f"strides={self.strides} padding={self.padding!r}"
                )
            # Env-gated stem→XLA hybrid (PCNN_PALLAS_STEM_XLA=1): the
            # documented escape hatch if a Mosaic regression re-opens
            # the huge-input stem compile pathology that row-band
            # tiling closes (docs/kernel_authoring.md).
            if pallas_conv.prefer_xla_fallback(
                self.kernel, self.strides, x.shape
            ):
                use_pallas = False
        if use_pallas:
            y = pallas_conv.conv2d(
                x, params["w"].astype(x.dtype), self.strides[0]
            )
        else:
            y = lax.conv_general_dilated(
                x,
                params["w"].astype(x.dtype),
                self.strides,
                self.padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
        if self.use_bias:
            y = y + params["b"].astype(x.dtype)
        return y, state


@dataclasses.dataclass(frozen=True)
class Dense(Module):
    features: int

    def init(self, key, in_shape: Shape):
        (d,) = in_shape
        wkey, _ = jax.random.split(key)
        params = {
            "w": _he_normal(wkey, (d, self.features), d, jnp.float32),
            "b": jnp.zeros((self.features,), jnp.float32),
        }
        return params, {}, (self.features,)

    def apply(self, params, state, x, train: bool = False):
        return x @ params["w"].astype(x.dtype) + params["b"].astype(x.dtype), state


@dataclasses.dataclass(frozen=True)
class BatchNorm(Module):
    """Running-stats batch norm; stats update only when train=True.

    The batch mean/var are global under GSPMD data parallelism (XLA
    all-reduces them when the batch is sharded) — true sync-BN for free.
    """

    momentum: float = 0.9
    eps: float = 1e-5

    def init(self, key, in_shape: Shape):
        c = in_shape[-1]
        params = {
            "scale": jnp.ones((c,), jnp.float32),
            "bias": jnp.zeros((c,), jnp.float32),
        }
        state = {
            "mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32),
        }
        return params, state, in_shape

    def apply(self, params, state, x, train: bool = False):
        axes = tuple(range(x.ndim - 1))
        if train:
            mean = jnp.mean(x.astype(jnp.float32), axis=axes)
            var = jnp.var(x.astype(jnp.float32), axis=axes)
            m = self.momentum
            state = {
                "mean": m * state["mean"] + (1 - m) * mean,
                "var": m * state["var"] + (1 - m) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
        # Statistics stay f32 (the reductions above consume the upcast
        # without materializing it), but the normalization's ELEMENTWISE
        # arithmetic runs at x's dtype: the previous form
        # ((x.astype(f32) − mean)·inv + bias).astype upcast the whole
        # (B,H,W,C) activation to f32 — doubling the elementwise HBM
        # traffic of every BN in bf16 mode, a candidate in the ResNet-50
        # MFU gap (VERDICT r3 weak #2). Order matters for bf16 rounding:
        # subtract mean FIRST so the product (x−mean)·inv rounds at the
        # O(1) normalized magnitude, not at |x·inv| ~ |mean/std| (a
        # folded y = x·inv + shift form measured 2-4× worse channel
        # rounding for large-|mean| channels). f32 inputs are bit-
        # identical to the old path (the casts are no-ops).
        inv = lax.rsqrt(var + self.eps) * params["scale"]
        y = (
            (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
            + params["bias"].astype(x.dtype)
        )
        return y, state


@dataclasses.dataclass(frozen=True)
class ConvBNAct(Module):
    """Conv2D(use_bias=False) → BatchNorm → (+ residual) → optional ReLU
    as ONE module, so backend="pallas" can execute the entire layer tail
    as a single fused kernel (`ops.pallas_conv.conv2d_fused`) in
    inference mode: the running-stats BN folds to per-channel
    scale/shift, and the residual add + ReLU ride the conv kernel's f32
    accumulator before its only HBM write — one round-trip per layer
    instead of three-to-four (≙ the reference CUDA kernels' fused
    bias+activation, CUDA/layer.cu:151-165).

    Training keeps the exact unfused composition: train-mode BN
    statistics are reductions OVER the conv output, so a one-pass
    fusion is mathematically impossible without changing the batch-stat
    semantics (docs/kernel_authoring.md). Gradients through the fused
    eval path (e.g. frozen-BN fine-tuning) are exact — conv2d_fused
    carries a full custom VJP.

    `apply(..., residual=sc)` computes relu?(bn(conv(x)) + sc); the
    fused-vs-unfused numerics differ only by f32 fold rounding (the
    fused epilogue runs entirely on the f32 accumulator).
    """

    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    relu: bool = True
    momentum: float = 0.9
    eps: float = 1e-5
    backend: str = "xla"

    def _conv(self) -> Conv2D:
        return Conv2D(
            self.features,
            kernel=self.kernel,
            strides=self.strides,
            padding="SAME",
            use_bias=False,
            backend=self.backend,
        )

    def _bn(self) -> BatchNorm:
        return BatchNorm(momentum=self.momentum, eps=self.eps)

    def init(self, key, in_shape: Shape):
        k1, k2 = jax.random.split(key)
        cp, _, shape = self._conv().init(k1, in_shape)
        bp, bs, shape = self._bn().init(k2, shape)
        return {"conv": cp, "bn": bp}, {"bn": bs}, shape

    def apply(self, params, state, x, train: bool = False, residual=None):
        if self.backend == "pallas" and not train:
            from parallel_cnn_tpu.ops import pallas_conv

            if pallas_conv.supports(
                self.kernel, self.strides, "SAME"
            ) and not pallas_conv.prefer_xla_fallback(
                self.kernel, self.strides, x.shape
            ):
                bn_s = state["bn"]
                # Folded inference-mode BN: y = conv·scale + shift.
                scale = params["bn"]["scale"] * lax.rsqrt(
                    bn_s["var"] + self.eps
                )
                shift = params["bn"]["bias"] - bn_s["mean"] * scale
                y = pallas_conv.conv2d_fused(
                    x,
                    params["conv"]["w"].astype(x.dtype),
                    scale,
                    shift,
                    residual,
                    self.strides[0],
                    self.relu,
                )
                return y, state
        y, _ = self._conv().apply(params["conv"], {}, x, train)
        y, bn_s = self._bn().apply(params["bn"], state["bn"], y, train)
        if residual is not None:
            y = y + residual
        if self.relu:
            y = jax.nn.relu(y)
        return y, {"bn": bn_s}


@dataclasses.dataclass(frozen=True)
class ReLU(Module):
    def init(self, key, in_shape: Shape):
        return {}, {}, in_shape

    def apply(self, params, state, x, train: bool = False):
        return jax.nn.relu(x), state


def _pool_out(in_shape: Shape, window, strides, padding) -> Shape:
    h, w, c = in_shape
    if padding == "SAME":
        oh = -(-h // strides[0])
        ow = -(-w // strides[1])
    else:
        oh = (h - window[0]) // strides[0] + 1
        ow = (w - window[1]) // strides[1] + 1
    return (oh, ow, c)


@dataclasses.dataclass(frozen=True)
class MaxPool(Module):
    window: Tuple[int, int] = (2, 2)
    strides: Tuple[int, int] = (2, 2)
    padding: str = "VALID"

    def init(self, key, in_shape: Shape):
        return {}, {}, _pool_out(in_shape, self.window, self.strides, self.padding)

    def apply(self, params, state, x, train: bool = False):
        y = lax.reduce_window(
            x,
            -jnp.inf,
            lax.max,
            (1, *self.window, 1),
            (1, *self.strides, 1),
            self.padding,
        )
        return y, state


@dataclasses.dataclass(frozen=True)
class AvgPool(Module):
    window: Tuple[int, int] = (2, 2)
    strides: Tuple[int, int] = (2, 2)
    padding: str = "VALID"

    def init(self, key, in_shape: Shape):
        return {}, {}, _pool_out(in_shape, self.window, self.strides, self.padding)

    def apply(self, params, state, x, train: bool = False):
        dims = (1, *self.window, 1)
        strides = (1, *self.strides, 1)
        y = lax.reduce_window(
            x, jnp.zeros((), x.dtype), lax.add, dims, strides, self.padding
        )
        if self.padding == "SAME":
            # Edge windows overlap padding: divide by the per-window count
            # of VALID elements, not the full window size.
            ones = jnp.ones(x.shape[1:3], x.dtype)[None, :, :, None]
            counts = lax.reduce_window(
                ones, jnp.zeros((), x.dtype), lax.add, dims, strides,
                self.padding,
            )
            return y / counts, state
        return y / (self.window[0] * self.window[1]), state


@dataclasses.dataclass(frozen=True)
class GlobalAvgPool(Module):
    def init(self, key, in_shape: Shape):
        return {}, {}, (in_shape[-1],)

    def apply(self, params, state, x, train: bool = False):
        return jnp.mean(x, axis=(1, 2)), state


@dataclasses.dataclass(frozen=True)
class Flatten(Module):
    def init(self, key, in_shape: Shape):
        size = 1
        for d in in_shape:
            size *= d
        return {}, {}, (size,)

    def apply(self, params, state, x, train: bool = False):
        return x.reshape(x.shape[0], -1), state
