"""ResNet family (BASELINE.json configs #4/#5: ResNet-18 on CIFAR-10,
ResNet-50 on ImageNet-1k) — He et al. 2016, built from this package's
layers with a functional residual-block module.

CIFAR variants use the 3×3/stride-1 stem (no maxpool); ImageNet variants
the 7×7/stride-2 stem + 3×3 maxpool, per the paper.

Round 6: blocks are built from `ConvBNAct` units (conv→BN→[+residual]→
relu as one module) so the branch TAILS — the BN, the shortcut add, and
the post-add ReLU — execute inside the conv kernel's epilogue on the
pallas backend in inference mode (`ops.pallas_conv.conv2d_fused`): one
HBM round-trip per layer instead of three-to-four. Both backends share
the module structure, so parameter trees stay identical across
conv_backend choices (the cross-backend parity tests zip leaves
strictly)."""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax

from parallel_cnn_tpu.nn.core import Module, Sequential, Shape
from parallel_cnn_tpu.nn.layers import (
    ConvBNAct,
    Dense,
    GlobalAvgPool,
    MaxPool,
)


@dataclasses.dataclass(frozen=True)
class BasicBlock(Module):
    """Two 3×3 convs + identity/projection shortcut (ResNet-18/34).

    The shortcut feeds the tail ConvBNAct as its fused residual: the
    add and the post-add ReLU live in the second conv's epilogue."""

    features: int
    stride: int = 1
    conv_backend: str = "xla"

    def _parts(self):
        head = ConvBNAct(
            self.features, strides=(self.stride, self.stride),
            backend=self.conv_backend,
        )
        tail = ConvBNAct(self.features, backend=self.conv_backend)
        proj = ConvBNAct(
            self.features, kernel=(1, 1),
            strides=(self.stride, self.stride), relu=False,
            backend=self.conv_backend,
        )
        return head, tail, proj

    def init(self, key, in_shape: Shape):
        head, tail, proj = self._parts()
        k1, k2 = jax.random.split(key)
        k1a, k1b = jax.random.split(k1)
        hp, hs, mid_shape = head.init(k1a, in_shape)
        tp, ts, out_shape = tail.init(k1b, mid_shape)
        params = {"main": [hp, tp]}
        state = {"main": [hs, ts]}
        if self.stride != 1 or in_shape[-1] != self.features:
            pp, ps, _ = proj.init(k2, in_shape)
            params["proj"] = [pp]
            state["proj"] = [ps]
        return params, state, out_shape

    def apply(self, params, state, x, train: bool = False):
        head, tail, proj = self._parts()
        if "proj" in params:
            sc, ps = proj.apply(
                params["proj"][0], state["proj"][0], x, train
            )
        else:
            sc = x
        y, hs = head.apply(params["main"][0], state["main"][0], x, train)
        y, ts = tail.apply(
            params["main"][1], state["main"][1], y, train, residual=sc
        )
        new_state = {"main": [hs, ts]}
        if "proj" in params:
            new_state["proj"] = [ps]
        return y, new_state


@dataclasses.dataclass(frozen=True)
class Bottleneck(Module):
    """1×1 → 3×3 → 1×1(×4) bottleneck (ResNet-50/101/152); the wide
    final 1×1's epilogue carries the shortcut add + ReLU."""

    features: int  # bottleneck width; output is 4× this
    stride: int = 1
    conv_backend: str = "xla"
    EXPANSION = 4

    def _parts(self):
        out_ch = self.features * self.EXPANSION
        reduce = ConvBNAct(
            self.features, kernel=(1, 1), backend=self.conv_backend
        )
        mid = ConvBNAct(
            self.features, strides=(self.stride, self.stride),
            backend=self.conv_backend,
        )
        expand = ConvBNAct(
            out_ch, kernel=(1, 1), backend=self.conv_backend
        )
        proj = ConvBNAct(
            out_ch, kernel=(1, 1),
            strides=(self.stride, self.stride), relu=False,
            backend=self.conv_backend,
        )
        return reduce, mid, expand, proj

    def init(self, key, in_shape: Shape):
        reduce, mid, expand, proj = self._parts()
        k1, k2 = jax.random.split(key)
        ka, kb, kc = jax.random.split(k1, 3)
        rp, rs, s1 = reduce.init(ka, in_shape)
        mp, ms, s2 = mid.init(kb, s1)
        ep, es, out_shape = expand.init(kc, s2)
        params = {"main": [rp, mp, ep]}
        state = {"main": [rs, ms, es]}
        if self.stride != 1 or in_shape[-1] != self.features * self.EXPANSION:
            pp, ps, _ = proj.init(k2, in_shape)
            params["proj"] = [pp]
            state["proj"] = [ps]
        return params, state, out_shape

    def apply(self, params, state, x, train: bool = False):
        reduce, mid, expand, proj = self._parts()
        if "proj" in params:
            sc, ps = proj.apply(
                params["proj"][0], state["proj"][0], x, train
            )
        else:
            sc = x
        y, rs = reduce.apply(params["main"][0], state["main"][0], x, train)
        y, ms = mid.apply(params["main"][1], state["main"][1], y, train)
        y, es = expand.apply(
            params["main"][2], state["main"][2], y, train, residual=sc
        )
        new_state = {"main": [rs, ms, es]}
        if "proj" in params:
            new_state["proj"] = [ps]
        return y, new_state


def _stage(
    block_cls, features: int, count: int, stride: int, conv_backend: str
) -> Sequence[Module]:
    return [
        block_cls(features, stride if i == 0 else 1, conv_backend)
        for i in range(count)
    ]


def _resnet(
    block_cls,
    stage_sizes: Sequence[int],
    num_classes: int,
    cifar_stem: bool,
    conv_backend: str = "xla",
) -> Sequential:
    if cifar_stem:
        stem = [ConvBNAct(64, backend=conv_backend)]
    else:
        # Round 4: the 7×7-stride-2 stem joined the pallas kernel
        # library's coverage (ops/pallas_conv.py generalized tap
        # geometry), so conv_backend="pallas" now puts EVERY conv in
        # ResNet-50 on hand-written kernels; round 6 band-tiles its
        # rows so the 224² layout compiles in minutes and fuses its
        # BN+ReLU tail in eval. MaxPool stays XLA (pooling, not conv).
        stem = [
            ConvBNAct(64, kernel=(7, 7), strides=(2, 2),
                      backend=conv_backend),
            MaxPool(window=(3, 3), strides=(2, 2), padding="SAME"),
        ]
    layers = list(stem)
    for i, (features, count) in enumerate(zip((64, 128, 256, 512), stage_sizes)):
        layers += _stage(
            block_cls, features, count, 1 if i == 0 else 2, conv_backend
        )
    layers += [GlobalAvgPool(), Dense(num_classes)]
    return Sequential(layers)


def resnet18(
    num_classes: int = 10, cifar_stem: bool = True, conv_backend: str = "xla"
) -> Sequential:
    return _resnet(BasicBlock, (2, 2, 2, 2), num_classes, cifar_stem, conv_backend)


def resnet34(
    num_classes: int = 10, cifar_stem: bool = True, conv_backend: str = "xla"
) -> Sequential:
    return _resnet(BasicBlock, (3, 4, 6, 3), num_classes, cifar_stem, conv_backend)


def resnet50(
    num_classes: int = 1000, cifar_stem: bool = False, conv_backend: str = "xla"
) -> Sequential:
    return _resnet(Bottleneck, (3, 4, 6, 3), num_classes, cifar_stem, conv_backend)


def num_params(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
