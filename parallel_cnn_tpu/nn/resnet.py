"""ResNet family (BASELINE.json configs #4/#5: ResNet-18 on CIFAR-10,
ResNet-50 on ImageNet-1k) — He et al. 2016, built from this package's
layers with a functional residual-block module.

CIFAR variants use the 3×3/stride-1 stem (no maxpool); ImageNet variants
the 7×7/stride-2 stem + 3×3 maxpool, per the paper."""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import jax

from parallel_cnn_tpu.nn.core import Module, Sequential, Shape
from parallel_cnn_tpu.nn.layers import (
    BatchNorm,
    Conv2D,
    Dense,
    GlobalAvgPool,
    MaxPool,
    ReLU,
)


@dataclasses.dataclass(frozen=True)
class BasicBlock(Module):
    """Two 3×3 convs + identity/projection shortcut (ResNet-18/34)."""

    features: int
    stride: int = 1
    conv_backend: str = "xla"

    def _branches(self):
        main = Sequential(
            [
                Conv2D(self.features, strides=(self.stride, self.stride),
                       use_bias=False, backend=self.conv_backend),
                BatchNorm(),
                ReLU(),
                Conv2D(self.features, use_bias=False,
                       backend=self.conv_backend),
                BatchNorm(),
            ]
        )
        proj = Sequential(
            [
                Conv2D(
                    self.features,
                    kernel=(1, 1),
                    strides=(self.stride, self.stride),
                    use_bias=False,
                    backend=self.conv_backend,
                ),
                BatchNorm(),
            ]
        )
        return main, proj

    def init(self, key, in_shape: Shape):
        main, proj = self._branches()
        k1, k2 = jax.random.split(key)
        mp, ms, out_shape = main.init(k1, in_shape)
        params = {"main": mp}
        state = {"main": ms}
        if self.stride != 1 or in_shape[-1] != self.features:
            pp, ps, _ = proj.init(k2, in_shape)
            params["proj"] = pp
            state["proj"] = ps
        return params, state, out_shape

    def apply(self, params, state, x, train: bool = False):
        main, proj = self._branches()
        y, ms = main.apply(params["main"], state["main"], x, train)
        new_state = {"main": ms}
        if "proj" in params:
            sc, ps = proj.apply(params["proj"], state["proj"], x, train)
            new_state["proj"] = ps
        else:
            sc = x
        return jax.nn.relu(y + sc), new_state


@dataclasses.dataclass(frozen=True)
class Bottleneck(Module):
    """1×1 → 3×3 → 1×1(×4) bottleneck (ResNet-50/101/152)."""

    features: int  # bottleneck width; output is 4× this
    stride: int = 1
    conv_backend: str = "xla"
    EXPANSION = 4

    def _branches(self):
        out_ch = self.features * self.EXPANSION
        main = Sequential(
            [
                Conv2D(self.features, kernel=(1, 1), use_bias=False,
                       backend=self.conv_backend),
                BatchNorm(),
                ReLU(),
                Conv2D(
                    self.features,
                    strides=(self.stride, self.stride),
                    use_bias=False,
                    backend=self.conv_backend,
                ),
                BatchNorm(),
                ReLU(),
                Conv2D(out_ch, kernel=(1, 1), use_bias=False,
                       backend=self.conv_backend),
                BatchNorm(),
            ]
        )
        proj = Sequential(
            [
                Conv2D(
                    out_ch,
                    kernel=(1, 1),
                    strides=(self.stride, self.stride),
                    use_bias=False,
                    backend=self.conv_backend,
                ),
                BatchNorm(),
            ]
        )
        return main, proj

    def init(self, key, in_shape: Shape):
        main, proj = self._branches()
        k1, k2 = jax.random.split(key)
        mp, ms, out_shape = main.init(k1, in_shape)
        params = {"main": mp}
        state = {"main": ms}
        if self.stride != 1 or in_shape[-1] != self.features * self.EXPANSION:
            pp, ps, _ = proj.init(k2, in_shape)
            params["proj"] = pp
            state["proj"] = ps
        return params, state, out_shape

    def apply(self, params, state, x, train: bool = False):
        main, proj = self._branches()
        y, ms = main.apply(params["main"], state["main"], x, train)
        new_state = {"main": ms}
        if "proj" in params:
            sc, ps = proj.apply(params["proj"], state["proj"], x, train)
            new_state["proj"] = ps
        else:
            sc = x
        return jax.nn.relu(y + sc), new_state


def _stage(
    block_cls, features: int, count: int, stride: int, conv_backend: str
) -> Sequence[Module]:
    return [
        block_cls(features, stride if i == 0 else 1, conv_backend)
        for i in range(count)
    ]


def _resnet(
    block_cls,
    stage_sizes: Sequence[int],
    num_classes: int,
    cifar_stem: bool,
    conv_backend: str = "xla",
) -> Sequential:
    if cifar_stem:
        stem = [
            Conv2D(64, use_bias=False, backend=conv_backend),
            BatchNorm(),
            ReLU(),
        ]
    else:
        # Round 4: the 7×7-stride-2 stem joined the pallas kernel
        # library's coverage (ops/pallas_conv.py generalized tap
        # geometry), so conv_backend="pallas" now puts EVERY conv in
        # ResNet-50 on hand-written kernels. MaxPool stays XLA (pooling,
        # not conv).
        stem = [
            Conv2D(64, kernel=(7, 7), strides=(2, 2), use_bias=False,
                   backend=conv_backend),
            BatchNorm(),
            ReLU(),
            MaxPool(window=(3, 3), strides=(2, 2), padding="SAME"),
        ]
    layers = list(stem)
    for i, (features, count) in enumerate(zip((64, 128, 256, 512), stage_sizes)):
        layers += _stage(
            block_cls, features, count, 1 if i == 0 else 2, conv_backend
        )
    layers += [GlobalAvgPool(), Dense(num_classes)]
    return Sequential(layers)


def resnet18(
    num_classes: int = 10, cifar_stem: bool = True, conv_backend: str = "xla"
) -> Sequential:
    return _resnet(BasicBlock, (2, 2, 2, 2), num_classes, cifar_stem, conv_backend)


def resnet34(
    num_classes: int = 10, cifar_stem: bool = True, conv_backend: str = "xla"
) -> Sequential:
    return _resnet(BasicBlock, (3, 4, 6, 3), num_classes, cifar_stem, conv_backend)


def resnet50(
    num_classes: int = 1000, cifar_stem: bool = False, conv_backend: str = "xla"
) -> Sequential:
    return _resnet(Bottleneck, (3, 4, 6, 3), num_classes, cifar_stem, conv_backend)


def num_params(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
