"""Module protocol + Sequential combinator.

A Module is a value (dataclass) with two pure functions:

    init(key, in_shape) -> (params, state, out_shape)
    apply(params, state, x, train) -> (y, new_state)

`in_shape`/`out_shape` are per-sample shapes (no batch dim); `x` is always
batched (N, ...). params hold trainables; state holds non-trainables
(BatchNorm running stats). Layers without params/state use empty dicts so
pytree structures stay uniform and checkpoint/optimizer code needs no
special cases.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Sequence, Tuple

import jax

Params = Any
State = Any
Shape = Tuple[int, ...]


class Module:
    """Base class (interface only — subclasses are frozen dataclasses)."""

    def init(self, key: jax.Array, in_shape: Shape):
        raise NotImplementedError

    def apply(self, params, state, x, train: bool = False):
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class Sequential(Module):
    """Compose modules; params/state are lists aligned with `layers`."""

    layers: Sequence[Module]

    def init(self, key: jax.Array, in_shape: Shape):
        params: List[Params] = []
        state: List[State] = []
        shape = in_shape
        keys = jax.random.split(key, max(len(self.layers), 1))
        for layer, k in zip(self.layers, keys):
            p, s, shape = layer.init(k, shape)
            params.append(p)
            state.append(s)
        return params, state, shape

    def apply(self, params, state, x, train: bool = False):
        new_state: List[State] = []
        for layer, p, s in zip(self.layers, params, state, strict=True):
            x, s = layer.apply(p, s, x, train)
            new_state.append(s)
        return x, new_state
