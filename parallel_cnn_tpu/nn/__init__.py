"""Generic NN layer library — the model-zoo substrate.

The reference hard-codes one network as four global `Layer` objects and a
fixed kernel wiring (Sequential/Main.cpp:17-20,59-144); growing past LeNet
(BASELINE.json configs: CIFAR CNN, ResNet-18/50) needs real composable
layers. This package is a deliberately small functional module system:

- a layer is a `Module` with `init(key, in_shape) -> (params, state)` and
  `apply(params, state, x, train) -> (y, state)`; params and state are
  plain pytrees (state = BatchNorm running stats — kept separate so the
  optimizer never sees it);
- everything composes through `Sequential`; models are plain data, no
  metaclasses, no tracing magic — friendly to jit/vmap/shard_map/pjit.

NHWC layouts throughout (channels-last is the TPU-native conv layout) and
He/LeCun inits; compute stays f32/bf16-polymorphic via the input dtype.
"""

from parallel_cnn_tpu.nn.core import Module, Sequential  # noqa: F401
from parallel_cnn_tpu.nn.layers import (  # noqa: F401
    AvgPool,
    BatchNorm,
    Conv2D,
    Dense,
    Flatten,
    GlobalAvgPool,
    MaxPool,
    ReLU,
)
from parallel_cnn_tpu.nn import cifar, resnet, vgg  # noqa: F401
