"""VGG (Simonyan & Zisserman 2014) for the model zoo — the classic
plain-conv family alongside the ResNets, built from the same NHWC layer
library. Configuration D (VGG-16): thirteen 3×3 SAME convs in five
maxpooled stages, then the classifier.

Two heads, as is conventional:
- ``cifar_head=True`` (default): GlobalAvgPool → Dense(num_classes) —
  the compact adaptation every CIFAR recipe uses.
- ``cifar_head=False``: the original Flatten → 4096 → 4096 → classes
  MLP (param parity with torchvision ``vgg16``/``vgg16_bn`` — asserted
  in tests/test_zoo.py; dropout is omitted: it carries no parameters
  and the zoo's regularizer is augmentation + weight decay).

Convs keep bias=True even under BatchNorm, matching torchvision's VGG
so the parameter counts line up exactly. conv_backend="pallas" routes
every conv through the hand-written kernels (ops/pallas_conv.py — all
3×3 stride-1, the kernel family's cheapest case).
"""

from __future__ import annotations

from parallel_cnn_tpu.nn.core import Sequential
from parallel_cnn_tpu.nn.layers import (
    BatchNorm,
    Conv2D,
    Dense,
    Flatten,
    GlobalAvgPool,
    MaxPool,
    ReLU,
)

# Configuration D: channels per conv, "M" = 2×2 maxpool.
_VGG16 = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
          512, 512, 512, "M", 512, 512, 512, "M"]


def vgg16(
    num_classes: int = 10,
    batch_norm: bool = True,
    cifar_head: bool = True,
    conv_backend: str = "xla",
) -> Sequential:
    layers = []
    for v in _VGG16:
        if v == "M":
            layers.append(MaxPool(window=(2, 2), strides=(2, 2)))
            continue
        layers.append(Conv2D(v, backend=conv_backend))
        if batch_norm:
            layers.append(BatchNorm())
        layers.append(ReLU())
    if cifar_head:
        layers += [GlobalAvgPool(), Dense(num_classes)]
    else:
        layers += [
            Flatten(),
            Dense(4096), ReLU(),
            Dense(4096), ReLU(),
            Dense(num_classes),
        ]
    return Sequential(layers)
