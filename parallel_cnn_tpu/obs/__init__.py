"""Unified observability layer (docs/observability.md).

One :class:`Obs` bundle carries the three signal sinks every subsystem
shares — the span :class:`~parallel_cnn_tpu.obs.trace.Tracer` (Chrome
trace / Perfetto export), the
:class:`~parallel_cnn_tpu.obs.registry.MetricsRegistry`
(Prometheus-text + JSON exposition), and the
:class:`~parallel_cnn_tpu.obs.events.EventJournal` (append-only JSONL
with per-process sequence ids).  Hot paths take an ``obs=None`` keyword
and normalize with ``obs = obs or NOOP``: the default is the zero-cost
no-op bundle, so nothing is paid unless ``ObsConfig`` turned it on.

Spans wrap host-side dispatch only; nothing here ever runs inside a
jitted body (see the ``train.obs_batched_step`` jaxpr-rules entry).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from parallel_cnn_tpu.obs.events import (
    NOOP_JOURNAL,
    EventJournal,
    NoopJournal,
    conservation,
    merge_journals,
    read_journal,
)
from parallel_cnn_tpu.obs.registry import Counter, Gauge, MetricsRegistry
from parallel_cnn_tpu.obs.trace import (
    NOOP_TRACER,
    NoopTracer,
    Tracer,
    validate_nesting,
)

__all__ = [
    "Obs", "NOOP", "from_config",
    "Tracer", "NoopTracer", "NOOP_TRACER", "validate_nesting",
    "MetricsRegistry", "Counter", "Gauge",
    "EventJournal", "NoopJournal", "NOOP_JOURNAL",
    "read_journal", "merge_journals", "conservation",
]


class Obs:
    """The bundle threaded through trainer/zoo/serve hot paths."""

    __slots__ = ("tracer", "registry", "journal", "cfg", "enabled",
                 "trace_path", "metrics_path")

    def __init__(self, tracer, registry, journal, cfg=None,
                 enabled: bool = False, trace_path: Optional[str] = None,
                 metrics_path: Optional[str] = None):
        self.tracer = tracer
        self.registry = registry
        self.journal = journal
        self.cfg = cfg
        self.enabled = enabled
        self.trace_path = trace_path
        self.metrics_path = metrics_path

    def span(self, name: str, cat: str = "step", **args: Any):
        return self.tracer.span(name, cat, **args)

    def event(self, kind: str, **fields: Any):
        return self.journal.emit(kind, **fields)

    def finish(self) -> Dict[str, str]:
        """Export every configured artifact; returns {kind: path}."""
        out: Dict[str, str] = {}
        if self.trace_path and self.tracer.enabled:
            out["trace"] = self.tracer.export(self.trace_path)
        if self.journal.enabled:
            self.journal.close()
            if self.journal.path:
                out["journal"] = self.journal.path
        if self.metrics_path and self.registry is not None:
            out["metrics"] = self.registry.write_json(self.metrics_path)
        return out


NOOP = Obs(NOOP_TRACER, None, NOOP_JOURNAL, cfg=None, enabled=False)


def from_config(cfg, run: str = "run", process_index: int = 0,
                mirror_jax: Optional[bool] = None) -> Obs:
    """Build the live (or no-op) bundle from an ``ObsConfig``.

    ``cfg`` is ``Optional[config.ObsConfig]`` — ``None`` or a disabled
    config returns the shared :data:`NOOP` singleton.  ``run`` names the
    artifacts (``<dir>/<run>_trace.json`` etc.) so several phases of one
    process don't clobber each other.
    """
    if cfg is None or not cfg.enabled:
        return NOOP
    if mirror_jax is None:
        mirror_jax = cfg.jax_annotations
    if cfg.trace:
        tracer = Tracer(process_name=f"pcnn:{run}", mirror_jax=mirror_jax)
        journal = EventJournal(
            os.path.join(cfg.dir, f"{run}_journal.jsonl"),
            process_index=process_index,
        )
        trace_path = os.path.join(cfg.dir, f"{run}_trace.json")
    else:
        tracer = NOOP_TRACER
        journal = NOOP_JOURNAL
        trace_path = None
    return Obs(
        tracer, MetricsRegistry(), journal, cfg=cfg, enabled=True,
        trace_path=trace_path, metrics_path=cfg.metrics_json,
    )
