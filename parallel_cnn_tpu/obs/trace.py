"""Thread-safe host-side span tracer with Chrome-trace-event export.

Spans wrap *dispatch* on the host — they never run inside a jitted body,
so the compiled program is byte-identical with tracing on or off (the
jaxpr-rules entry ``train.obs_batched_step`` proves this invariant
statically).  Timing uses the monotonic ``time.perf_counter_ns`` clock;
every span records the calling thread, and per-thread/process track
metadata is emitted so the export loads in Perfetto / ``chrome://tracing``
with readable lanes.

Two export shapes are produced in one file:

- ``X`` (complete) events — one per closed span, ``ts``+``dur`` in
  microseconds.  Nesting is implied by containment per thread track and
  checked by :func:`validate_nesting`.
- ``b``/``e`` (async) events — request-flow spans that start and end on
  different threads (serve submit → complete), correlated by ``id``.

When ``mirror_jax=True`` each span also enters a
``jax.profiler.TraceAnnotation`` so XLA device profiles carry the same
semantic names as the host timeline; the import is guarded so the tracer
works in jax-free contexts (the analysis stubs).

The disabled path is a module singleton: :data:`NOOP_TRACER` returns the
same reusable :class:`_NoopSpan` object from every ``span()`` call — no
per-step allocations are retained, which the dryrun obs leg measures
with ``tracemalloc``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


class _NoopSpan:
    """Reusable do-nothing context manager (one instance per process)."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


_NOOP_SPAN = _NoopSpan()


class NoopTracer:
    """Zero-cost tracer used whenever observability is off."""

    __slots__ = ()
    enabled = False

    def span(self, name: str, cat: str = "step", **args: Any) -> _NoopSpan:
        return _NOOP_SPAN

    def instant(self, name: str, cat: str = "step", **args: Any) -> None:
        return None

    def begin_async(self, name: str, aid: int, cat: str = "req") -> None:
        return None

    def end_async(self, name: str, aid: int, cat: str = "req") -> None:
        return None

    def events(self) -> List[Dict[str, Any]]:
        return []

    def export(self, path: str) -> Optional[str]:
        return None


NOOP_TRACER = NoopTracer()


def _jax_annotation_cls():
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation
    except Exception:
        return None


class _Span:
    """One open span; closing records an ``X`` event on the tracer."""

    __slots__ = ("_tracer", "name", "cat", "args", "_t0_ns", "_mirror")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._t0_ns = 0
        self._mirror = None

    def __enter__(self) -> "_Span":
        cls = self._tracer._mirror_cls
        if cls is not None:
            self._mirror = cls(self.name)
            self._mirror.__enter__()
        self._t0_ns = time.perf_counter_ns()
        return self

    def __exit__(self, *exc: Any) -> bool:
        t1 = time.perf_counter_ns()
        if self._mirror is not None:
            self._mirror.__exit__(*exc)
        self._tracer._record_complete(
            self.name, self.cat, self._t0_ns, t1, self.args
        )
        return False


class Tracer:
    """Collects Chrome-trace events from any number of threads."""

    enabled = True

    def __init__(self, process_name: str = "parallel_cnn_tpu",
                 pid: Optional[int] = None, mirror_jax: bool = False,
                 replica: Optional[int] = None):
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._pid = os.getpid() if pid is None else int(pid)
        self._named_tids: set = set()
        self._mirror_cls = _jax_annotation_cls() if mirror_jax else None
        track = process_name if replica is None else (
            f"{process_name}/replica{replica}"
        )
        self._events.append({
            "ph": "M", "name": "process_name", "pid": self._pid, "tid": 0,
            "args": {"name": track},
        })

    # -- recording ---------------------------------------------------------

    def span(self, name: str, cat: str = "step", **args: Any) -> _Span:
        return _Span(self, name, cat, args)

    def _thread_meta_locked(self, tid: int) -> None:
        if tid not in self._named_tids:
            self._named_tids.add(tid)
            self._events.append({
                "ph": "M", "name": "thread_name", "pid": self._pid,
                "tid": tid,
                "args": {"name": threading.current_thread().name},
            })

    def _record_complete(self, name: str, cat: str, t0_ns: int, t1_ns: int,
                         args: Dict[str, Any]) -> None:
        tid = threading.get_ident()
        ev = {
            "ph": "X", "name": name, "cat": cat, "pid": self._pid,
            "tid": tid, "ts": t0_ns / 1e3, "dur": (t1_ns - t0_ns) / 1e3,
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._thread_meta_locked(tid)
            self._events.append(ev)

    def instant(self, name: str, cat: str = "step", **args: Any) -> None:
        tid = threading.get_ident()
        ev = {
            "ph": "i", "name": name, "cat": cat, "pid": self._pid,
            "tid": tid, "ts": time.perf_counter_ns() / 1e3, "s": "t",
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._thread_meta_locked(tid)
            self._events.append(ev)

    def _async(self, ph: str, name: str, aid: int, cat: str) -> None:
        tid = threading.get_ident()
        ev = {
            "ph": ph, "name": name, "cat": cat, "pid": self._pid,
            "tid": tid, "ts": time.perf_counter_ns() / 1e3,
            "id": f"{aid:#x}",
        }
        with self._lock:
            self._thread_meta_locked(tid)
            self._events.append(ev)

    def begin_async(self, name: str, aid: int, cat: str = "req") -> None:
        self._async("b", name, aid, cat)

    def end_async(self, name: str, aid: int, cat: str = "req") -> None:
        self._async("e", name, aid, cat)

    # -- export ------------------------------------------------------------

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def export(self, path: str) -> str:
        """Write the Chrome-trace JSON; returns the path written."""
        payload = {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
        }
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(payload, f)
        return path


def validate_nesting(events: List[Dict[str, Any]]) -> List[str]:
    """Check that ``X`` spans nest properly per (pid, tid) track.

    Proper nesting means: for any two spans on one thread, their
    [ts, ts+dur] intervals are either disjoint or one contains the
    other — partial overlap would mean a span closed out of order.
    Returns a list of violation descriptions (empty = valid).
    """
    problems: List[str] = []
    by_track: Dict[tuple, List[Dict[str, Any]]] = {}
    for ev in events:
        if ev.get("ph") == "X":
            by_track.setdefault((ev["pid"], ev["tid"]), []).append(ev)
    for track, evs in by_track.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: List[Dict[str, Any]] = []
        for ev in evs:
            end = ev["ts"] + ev["dur"]
            while stack and ev["ts"] >= stack[-1]["ts"] + stack[-1]["dur"]:
                stack.pop()
            if stack:
                parent_end = stack[-1]["ts"] + stack[-1]["dur"]
                if end > parent_end:
                    problems.append(
                        f"track {track}: span '{ev['name']}' "
                        f"[{ev['ts']}, {end}] partially overlaps "
                        f"'{stack[-1]['name']}' ending at {parent_end}"
                    )
            stack.append(ev)
    return problems
