"""Process-wide metrics registry with Prometheus-text and JSON exposition.

Three primitive kinds — monotonically increasing :class:`Counter`,
last-value :class:`Gauge`, and the existing streaming
``utils.metrics.Histogram`` (log-binned, O(1) record, mergeable) — plus
*collectors*: callables returning a flat-or-nested dict snapshot, which
is how legacy stat objects (``serve.telemetry.ServeStats``) join the
same exposition path without changing their counter semantics.

Cross-host merge composes from the primitives' own semantics: counters
sum, gauges take the max (the conservative "worst replica" reading for
depth/occupancy-style values), histograms fold via ``Histogram.merge``
(which raises on binning mismatch, so silently incompatible merges are
impossible).
"""

from __future__ import annotations

import json
import re
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from parallel_cnn_tpu.utils.metrics import Histogram

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return _NAME_RE.sub("_", name)


class Counter:
    """Monotonic counter; ``inc`` is thread-safe."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-value gauge; ``set`` is thread-safe."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


def _flatten(prefix: str, obj: Any, out: Dict[str, float]) -> None:
    """Flatten a nested snapshot dict to dotted numeric leaves."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix] = float(obj)


class MetricsRegistry:
    """Name → metric map shared by train and serve hot paths."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}
        self._collectors: Dict[str, Callable[[], Dict[str, Any]]] = {}

    # -- get-or-create -----------------------------------------------------

    def counter(self, name: str, help: str = "") -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name, help)
            return c

    def gauge(self, name: str, help: str = "") -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, help)
            return g

    def histogram(self, name: str, help: str = "", lo: float = 1e-5,
                  hi: float = 100.0, bins: int = 96) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(lo=lo, hi=hi, bins=bins)
            return h

    def attach(self, name: str,
               collect: Callable[[], Dict[str, Any]]) -> None:
        """Register a snapshot provider; its dict is flattened into the
        exposition under ``name.<key>`` leaves at read time."""
        with self._lock:
            self._collectors[name] = collect

    # -- exposition --------------------------------------------------------

    def _snapshot_parts(self) -> Tuple[
        List[Counter], List[Gauge], List[Tuple[str, Histogram]],
        List[Tuple[str, Callable[[], Dict[str, Any]]]],
    ]:
        with self._lock:
            return (
                list(self._counters.values()),
                list(self._gauges.values()),
                list(self._hists.items()),
                list(self._collectors.items()),
            )

    def json_snapshot(self) -> Dict[str, Any]:
        counters, gauges, hists, collectors = self._snapshot_parts()
        out: Dict[str, Any] = {
            "counters": {c.name: c.value for c in counters},
            "gauges": {g.name: g.value for g in gauges},
            "histograms": {name: h.summary() for name, h in hists},
        }
        for name, collect in collectors:
            out.setdefault("collected", {})[name] = collect()
        return out

    def prometheus_text(self) -> str:
        counters, gauges, hists, collectors = self._snapshot_parts()
        lines: List[str] = []
        for c in counters:
            n = _prom_name(c.name)
            if c.help:
                lines.append(f"# HELP {n} {c.help}")
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n} {c.value}")
        for g in gauges:
            n = _prom_name(g.name)
            if g.help:
                lines.append(f"# HELP {n} {g.help}")
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {g.value}")
        for name, h in hists:
            n = _prom_name(name)
            lines.append(f"# TYPE {n} summary")
            s = h.summary()
            for q in (50, 90, 99):
                if f"p{q}" in s:
                    lines.append(
                        f'{n}{{quantile="0.{q}"}} {s[f"p{q}"]}'
                    )
            lines.append(f"{n}_count {s['count']}")
            lines.append(f"{n}_sum {h.sum}")
        for name, collect in collectors:
            flat: Dict[str, float] = {}
            _flatten(name, collect(), flat)
            for key in sorted(flat):
                n = _prom_name(key)
                lines.append(f"# TYPE {n} gauge")
                lines.append(f"{n} {flat[key]}")
        return "\n".join(lines) + "\n"

    def write_json(self, path: str) -> str:
        import os

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.json_snapshot(), f, indent=2, sort_keys=True)
        return path

    # -- cross-host merge --------------------------------------------------

    def merge(self, other: "MetricsRegistry") -> None:
        """Fold another host's registry into this one: counters sum,
        gauges take max, histograms ``Histogram.merge`` (binning
        mismatch raises).  Collectors are process-local and not merged."""
        counters, gauges, hists, _ = other._snapshot_parts()
        for c in counters:
            self.counter(c.name, c.help).inc(c.value)
        for g in gauges:
            mine = self.gauge(g.name, g.help)
            mine.set(max(mine.value, g.value))
        for name, h in hists:
            self.histogram(name, lo=h.lo, hi=h.hi, bins=h.bins).merge(h)
