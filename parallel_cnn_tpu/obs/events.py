"""Append-only JSONL event journal with deterministic multi-host merge.

Every record carries a monotonically increasing per-process sequence id
(``seq``) plus the process index (``proc``), so journals from several
hosts merge deterministically by ``(proc, seq)`` — wall-clock timestamps
(``ts``) ride along for humans but never order the merge (clocks skew;
sequence ids don't).

Event kinds written by the wired hot paths: ``epoch`` / ``step_loss``
(trainer + zoo), ``loss_scale`` (dynamic loss-scaling skip/rescale),
``verdict`` (sentinel health checks), ``rollback``, ``checkpoint``,
``preempt``, ``chaos`` (injections), ``comm_plan`` / ``comm_bucket``
(bucket schedule), ``aot_compile`` (serve engine), the elastic runtime's
``resize_begin`` / ``resize_done`` (old/new world + host counts, trigger
source, ring fallback — bracketing the ``train.resize`` span) and the
failover path's ``replica_evicted`` / ``failover`` /
``replica_respawned``, and the request lifecycle ``submit`` / ``shed``
/ ``expired`` / ``batch`` / ``complete`` / ``failed`` — whose counts
obey the same conservation law as ``ServeStats``: submitted ==
completed + shed + expired + failed (and must keep obeying it across a
mid-traffic replica death: failover re-resolves, never duplicates).
The SLO-guarded serving layer adds ``admission_level`` (degradation-
ladder transitions, serve/admission.py), ``scale_up`` / ``scale_down``
(autoscaler decisions, serve/autoscaler.py), and ``chaos_slow_replica``
(straggler injection, the slow-replica twin of the chaos kill).
The async data-parallel trainer (train/async_dp.py) adds
``chaos_slow_worker`` (the training twin of ``chaos_slow_replica``,
injected at the microbatch dispatch boundary), ``straggler_detected``
(a completion exceeded ``straggler_factor`` x the nominal step
duration), ``staleness`` (per optimizer step: the group's max snapshot
age and whether the hard barrier fired), ``easgd_round`` (one elastic-
averaging ρ-pull, bracketed by the ``train.easgd_round`` span), and
``sentinel_drop`` (a poisoned worker gradient rejected before it could
reach the server/center params).
The network front door (serve/net.py + serve/supervisor.py) adds a
wire-tier request lifecycle ``net_submit`` / ``net_complete`` /
``net_shed`` / ``net_expired`` / ``net_failed`` — obeying the same
conservation law under the ``net_`` prefix (``conservation(counts,
prefix="net_")``) — plus ``conn_open`` (connection accepted),
``conn_expired`` (a stalled/slow-loris connection reaped at the read
deadline, its partial request counted ``net_expired``),
``endpoint_killed`` (endpoint death; in-flight wire requests journaled
``net_failed``, never silently lost), ``endpoint_respawned``
(supervisor restart, with downtime), ``hot_swap_begin`` /
``hot_swap_done`` (zero-downtime weight swap bracket), and the
engine's persistent executable cache ``aot_cache_hit`` /
``aot_cache_miss`` / ``aot_cache_corrupt`` (torn, damaged, or
fingerprint-mismatched entries degrade to recompile with a typed
warning).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence


class NoopJournal:
    """Zero-cost journal used whenever observability is off."""

    __slots__ = ()
    enabled = False
    path = None

    def emit(self, kind: str, **fields: Any) -> None:
        return None

    def counts(self) -> Dict[str, int]:
        return {}

    def flush(self) -> None:
        return None

    def close(self) -> None:
        return None


NOOP_JOURNAL = NoopJournal()


class EventJournal:
    """Thread-safe append-only JSONL sink with per-kind counting."""

    enabled = True

    def __init__(self, path: str, process_index: int = 0):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self.path = path
        self.process_index = int(process_index)
        self._lock = threading.Lock()
        self._f = open(path, "a")
        self._seq = 0
        self._counts: Dict[str, int] = {}

    def emit(self, kind: str, **fields: Any) -> Dict[str, Any]:
        rec: Dict[str, Any] = dict(fields)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            rec["proc"] = self.process_index
            rec["kind"] = kind
            rec["ts"] = time.time()
            self._f.write(json.dumps(rec) + "\n")
            self._counts[kind] = self._counts.get(kind, 0) + 1
        return rec

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def flush(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


def read_journal(path: str) -> List[Dict[str, Any]]:
    """Parse one journal file; blank lines are skipped."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def merge_journals(paths: Sequence[str]) -> List[Dict[str, Any]]:
    """Deterministic multi-host merge: stable order by ``(proc, seq)``,
    independent of file order and wall-clock skew."""
    records: List[Dict[str, Any]] = []
    for p in paths:
        records.extend(read_journal(p))
    records.sort(key=lambda r: (r.get("proc", 0), r.get("seq", 0)))
    return records


def conservation(counts: Dict[str, int], prefix: str = "") -> Optional[str]:
    """Check the serve lifecycle conservation law over per-kind counts.

    Returns None when conserved (or when no submits were journaled),
    else a human-readable description of the imbalance. ``prefix``
    selects which tier's lifecycle to check: ``""`` for the batcher
    tier (``submit``/``complete``/...), ``"net_"`` for the wire tier
    journaled by serve/net.py (``net_submit``/``net_complete``/...).
    """
    submitted = counts.get(prefix + "submit", 0)
    if submitted == 0:
        return None
    accounted = (
        counts.get(prefix + "complete", 0) + counts.get(prefix + "shed", 0)
        + counts.get(prefix + "expired", 0)
        + counts.get(prefix + "failed", 0)
    )
    if accounted != submitted:
        return (
            f"journal conservation violated: {prefix}submit={submitted} != "
            f"{prefix}complete+shed+expired+failed={accounted}"
        )
    return None
