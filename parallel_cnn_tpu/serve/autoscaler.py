"""Replica autoscaler: a windowed-signal control loop over ReplicaPool.

The control problem: the serving stack's capacity knob is the replica
count, but the signals that say "wrong size" (shed rate, p99, batch
occupancy) are noisy and lag the load. The loop therefore reads the
*windowed* ServeStats views (exponentially decayed — recent traffic
dominates, serve/telemetry.py) and applies two classic stabilizers:

- **hysteresis** — a direction must persist for ``hysteresis``
  consecutive ticks before the loop acts, so a single noisy window
  cannot trigger a resize;
- **cooldown** — after any action, no further action for
  ``cooldown_s``, so the loop observes the *consequence* of a resize
  before considering the next one (the no-flapping guarantee: at most
  one direction change per cooldown window).

Scale-up reuses the failover machinery: ``ReplicaPool.grow`` revives a
retired slot via the respawn path (or appends a fresh pinned Engine)
and the batcher gains a runner thread so the new replica can actually
hold a batch in flight. Scale-down is drain-then-retire: the victim
becomes unroutable (``pool.drain``), the loop waits for its in-flight
count to reach zero (``batcher.inflight``), then frees the slot —
zero in-flight requests are lost by construction.

The reactive loop is by construction *late*: it waits for a symptom
(p99 bust, shed) and then pays hysteresis ticks.  Passing a
``capacity`` planner (serve/capacity.py) adds a **predictive**
feed-forward branch: when the planner's replicas-needed estimate —
arrival-rate EWMA over per-replica service rate, with headroom —
exceeds the routable count, the loop scales up immediately, *before*
the windowed p99 busts the SLO.  The predictive branch skips
hysteresis (the EWMAs are the noise filter) but still honours the
cooldown and ``max_replicas``; while the planner is cold it returns
``None`` and the reactive classifier is the only voice.  With
``capacity=None`` the loop is exactly the PR 11 reactive scaler.

Every decision lands in the event journal (``scale_up`` /
``scale_down`` events, each carrying the ``reason`` —
"predictive"/"reactive") and the metrics registry
(``attach_registry``), so a capacity timeline is reconstructable from
the obs artifacts.

``tick()`` is the testable unit (no thread, injectable clock);
``start()``/``close()`` wrap it in the background control loop.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from parallel_cnn_tpu import obs as obs_lib


class AutoScaler:
    """Grows/shrinks a ReplicaPool between ``min_replicas`` and
    ``max_replicas`` from the batcher's windowed telemetry.

    Overload: windowed shed rate > ``shed_high`` OR windowed p99 >
    ``slo_ms``. Underload: no recent sheds, p99 comfortably inside the
    SLO, and batch occupancy below ``occupancy_low`` (or no traffic at
    all) — capacity is padding batches instead of serving them.
    """

    def __init__(
        self,
        pool,
        batcher,
        *,
        min_replicas: int = 1,
        max_replicas: int = 2,
        slo_ms: float = 100.0,
        shed_high: float = 0.05,
        occupancy_low: float = 0.30,
        hysteresis: int = 2,
        cooldown_s: float = 2.0,
        interval_s: float = 0.25,
        drain_timeout_s: float = 10.0,
        capacity=None,
        obs: Optional["obs_lib.Obs"] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}"
            )
        if hysteresis < 1:
            raise ValueError(f"hysteresis must be >= 1, got {hysteresis}")
        if cooldown_s < 0 or interval_s <= 0:
            raise ValueError("cooldown_s must be >= 0, interval_s > 0")
        self.pool = pool
        self.batcher = batcher
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.slo_ms = slo_ms
        self.shed_high = shed_high
        self.occupancy_low = occupancy_low
        self.hysteresis = hysteresis
        self.cooldown_s = cooldown_s
        self.interval_s = interval_s
        self.drain_timeout_s = drain_timeout_s
        #: Optional serve.capacity.CapacityModel — enables the
        #: predictive feed-forward branch of tick().
        self.capacity = capacity
        self.obs = obs if obs is not None else obs_lib.NOOP
        self._clock = clock
        self._lock = threading.Lock()
        self._up_streak = 0
        self._down_streak = 0
        self._predictive_ups = 0
        self._last_action_t: Optional[float] = None
        #: (t, direction, replica) decision log — tests replay it.
        self.actions: List[Tuple[float, str, int]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the control step -----------------------------------------------

    def _classify(self) -> Optional[str]:
        """"up", "down", or None from the windowed signals."""
        stats = self.batcher.stats
        shed = stats.window_shed_rate()
        p99 = stats.window_p99_ms()
        occ = stats.window_occupancy()
        if shed > self.shed_high or (p99 is not None and p99 > self.slo_ms):
            return "up"
        if shed <= 1e-9 and (p99 is None or p99 <= 0.5 * self.slo_ms) \
                and (occ is None or occ < self.occupancy_low):
            return "down"
        return None

    def tick(self) -> Optional[str]:
        """One control step; returns the action taken ("up"/"down") or
        None. Hysteresis and cooldown are enforced here, so calling
        tick() faster changes nothing but reaction latency."""
        now = self._clock()
        # Feed-forward first: if the capacity planner predicts demand
        # beyond the routable fleet, grow NOW — no hysteresis (the
        # planner's EWMAs are the noise filter), but cooldown and
        # max_replicas still bound the step.  A cold planner returns
        # None and the reactive classifier below is the only voice.
        if self.capacity is not None:
            with self._lock:
                in_cooldown = (
                    self._last_action_t is not None
                    and now - self._last_action_t < self.cooldown_s
                )
            if not in_cooldown:
                needed = self.capacity.replicas_needed()
                if needed is not None and needed > len(self.pool.routable()):
                    acted = self._scale_up(now, reason="predictive")
                    if acted is not None:
                        with self._lock:
                            self._predictive_ups += 1
                        return acted
        want = self._classify()
        with self._lock:
            if want == "up":
                self._up_streak += 1
                self._down_streak = 0
            elif want == "down":
                self._down_streak += 1
                self._up_streak = 0
            else:
                self._up_streak = 0
                self._down_streak = 0
            in_cooldown = (
                self._last_action_t is not None
                and now - self._last_action_t < self.cooldown_s
            )
            act_up = (not in_cooldown
                      and self._up_streak >= self.hysteresis)
            act_down = (not in_cooldown and not act_up
                        and self._down_streak >= self.hysteresis)
        if act_up:
            return self._scale_up(now)
        if act_down:
            return self._scale_down(now)
        return None

    def _record(self, now: float, direction: str, replica: int) -> None:
        with self._lock:
            self._last_action_t = now
            self._up_streak = 0
            self._down_streak = 0
            self.actions.append((now, direction, replica))

    def _scale_up(self, now: float, reason: str = "reactive") -> Optional[str]:
        if len(self.pool.routable()) >= self.max_replicas:
            return None
        i = self.pool.grow()
        # A grown slot beyond the runner count needs its own runner
        # thread (a revived slot reuses the one it always had).
        while self.pool.n_replicas > self.batcher.n_runners:
            self.batcher.add_runner()
        self._record(now, "up", i)
        if self.obs.enabled:
            self.obs.event("scale_up", replica=i, reason=reason,
                           routable=len(self.pool.routable()))
        return "up"

    def _scale_down(self, now: float) -> Optional[str]:
        routable = self.pool.routable()
        if len(routable) <= self.min_replicas:
            return None
        victim = routable[-1]
        self.pool.drain(victim)
        # Drain barrier: wait for the victim's in-flight batches to
        # resolve; nothing new routes to it once draining.
        deadline = time.monotonic() + self.drain_timeout_s
        while self.batcher.inflight(victim) > 0:
            if time.monotonic() > deadline:
                # In-flight work would not finish — undo the drain
                # rather than retire a busy replica.
                self.pool.respawn(victim)
                return None
            time.sleep(0.001)
        self.pool.retire(victim)
        self._record(now, "down", victim)
        if self.obs.enabled:
            self.obs.event("scale_down", replica=victim, reason="reactive",
                           routable=len(self.pool.routable()))
        return "down"

    # -- lifecycle + exposition -----------------------------------------

    def start(self) -> "AutoScaler":
        with self._lock:
            if self._thread is not None:
                return self
            self._thread = threading.Thread(
                target=self._loop, name="serve-autoscaler", daemon=True
            )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(self.interval_s)

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout=5)

    def __enter__(self) -> "AutoScaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def direction_changes(self) -> int:
        """Number of up↔down flips in the decision log (the flapping
        metric the no-flapping acceptance gate pins)."""
        with self._lock:
            dirs = [d for _, d, _ in self.actions]
        return sum(1 for a, b in zip(dirs, dirs[1:]) if a != b)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            ups = sum(1 for _, d, _ in self.actions if d == "up")
            downs = sum(1 for _, d, _ in self.actions if d == "down")
            predictive = self._predictive_ups
        return {
            "routable": len(self.pool.routable()),
            "min": self.min_replicas,
            "max": self.max_replicas,
            "scale_ups": ups,
            "scale_downs": downs,
            "predictive_ups": predictive,
            "direction_changes": self.direction_changes(),
        }

    def attach_registry(self, registry, prefix: str = "autoscaler") -> None:
        """Expose the decision counters through an obs.MetricsRegistry
        (same pull-collector convention as ServeStats)."""
        registry.attach(prefix, self.snapshot)
