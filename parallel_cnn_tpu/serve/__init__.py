"""Inference serving subsystem: checkpoint → AOT-compiled, shape-bucketed,
dynamically batched, replica-sharded predict — the layer that turns the
training stack's checkpoints into a traffic-serving surface (ROADMAP
north star; docs/serving.md for the design).

    registry   name → uniform (init, forward, in_shape) model handle
    engine     AOT per-bucket compile cache, BN folded at compile time,
               device-pinned replicas (Engine / ReplicaPool)
    batcher    bounded queue + deadline-aware dynamic batching with
               typed Overloaded backpressure (DynamicBatcher)
    telemetry  latency percentiles, queue depth, occupancy, shed rate —
               lifetime and windowed (decayed) views
    admission  SLO admission control: EWMA reject-early shedding +
               the graceful-degradation ladder (AdmissionController)
    autoscaler hysteresis/cooldown control loop growing/draining the
               ReplicaPool from windowed telemetry, with an optional
               predictive feed-forward branch (AutoScaler)
    capacity   predictive capacity planner: chosen serve plan +
               admission EWMAs → replicas-needed (CapacityModel)
    scenarios  seeded traffic scenarios with explicit p99/shed gates
               (diurnal, flash-crowd, slow-client, chaos-kill/slow)
               plus the net suites judged at the wire tier
    loadgen    seeded closed-/open-loop traffic + client retry protocol
               (in-process and over the socket transport)
    net        stdlib TCP front door: NDJSON protocol, per-connection
               read/write deadlines, slow-loris reaping, wire-tier
               conservation (NetServer / WireStats)
    supervisor crash-fast respawn with bounded backoff on a stable
               port, and the zero-downtime weight hot_swap roll
"""

from parallel_cnn_tpu.serve.admission import AdmissionController  # noqa: F401
from parallel_cnn_tpu.serve.autoscaler import AutoScaler  # noqa: F401
from parallel_cnn_tpu.serve.capacity import CapacityModel  # noqa: F401
from parallel_cnn_tpu.serve.batcher import (  # noqa: F401
    DeadlineExceeded,
    DynamicBatcher,
    Future,
    Overloaded,
    serve_stack,
)
from parallel_cnn_tpu.serve.engine import (  # noqa: F401
    AotCacheWarning,
    Engine,
    EngineStats,
    ReplicaPool,
    bucket_for,
    load_or_init,
)
from parallel_cnn_tpu.serve.net import NetServer  # noqa: F401
from parallel_cnn_tpu.serve.registry import ModelHandle, available, get  # noqa: F401
from parallel_cnn_tpu.serve.scenarios import (  # noqa: F401
    NET_SCENARIOS,
    SCENARIOS,
    NetScenarioReport,
    ScenarioReport,
)
from parallel_cnn_tpu.serve.supervisor import Supervisor, hot_swap  # noqa: F401
from parallel_cnn_tpu.serve.telemetry import ServeStats, WireStats  # noqa: F401
