"""Inference serving subsystem: checkpoint → AOT-compiled, shape-bucketed,
dynamically batched, replica-sharded predict — the layer that turns the
training stack's checkpoints into a traffic-serving surface (ROADMAP
north star; docs/serving.md for the design).

    registry   name → uniform (init, forward, in_shape) model handle
    engine     AOT per-bucket compile cache, BN folded at compile time,
               device-pinned replicas (Engine / ReplicaPool)
    batcher    bounded queue + deadline-aware dynamic batching with
               typed Overloaded backpressure (DynamicBatcher)
    telemetry  latency percentiles, queue depth, occupancy, shed rate —
               lifetime and windowed (decayed) views
    admission  SLO admission control: EWMA reject-early shedding +
               the graceful-degradation ladder (AdmissionController)
    autoscaler hysteresis/cooldown control loop growing/draining the
               ReplicaPool from windowed telemetry (AutoScaler)
    scenarios  seeded traffic scenarios with explicit p99/shed gates
               (diurnal, flash-crowd, slow-client, chaos-kill/slow)
    loadgen    seeded closed-/open-loop traffic + client retry protocol
"""

from parallel_cnn_tpu.serve.admission import AdmissionController  # noqa: F401
from parallel_cnn_tpu.serve.autoscaler import AutoScaler  # noqa: F401
from parallel_cnn_tpu.serve.batcher import (  # noqa: F401
    DeadlineExceeded,
    DynamicBatcher,
    Future,
    Overloaded,
    serve_stack,
)
from parallel_cnn_tpu.serve.engine import (  # noqa: F401
    Engine,
    EngineStats,
    ReplicaPool,
    bucket_for,
    load_or_init,
)
from parallel_cnn_tpu.serve.registry import ModelHandle, available, get  # noqa: F401
from parallel_cnn_tpu.serve.scenarios import (  # noqa: F401
    SCENARIOS,
    ScenarioReport,
)
from parallel_cnn_tpu.serve.telemetry import ServeStats  # noqa: F401
