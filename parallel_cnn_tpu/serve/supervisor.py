"""Endpoint supervision and zero-downtime weight hot-swap.

Two recovery paths for the network front door (serve/net.py), both
built from machinery the repo already trusts:

- :class:`Supervisor` — crash-fast cold restart. A monitor thread
  watches the endpoint; when it dies (``kill-endpoint@`` chaos, or any
  abrupt ``kill()``), the supervisor respawns it **on the same port**
  under the bounded, seeded exponential backoff of
  ``resilience.retry.RetryPolicy`` — no infinite respawn loops by
  construction. The respawn is journaled ``endpoint_respawned`` with
  the measured downtime; the killed endpoint already journaled its
  in-flight wire requests as ``net_failed`` (net.py), so the journal
  reconciles exactly across the restart: nothing is silently lost, and
  the wire conservation law — computed over the WireStats *shared*
  across incarnations — keeps holding.

- :func:`hot_swap` — zero-downtime weight replacement. New weights go
  to ``ReplicaPool.set_weights`` (so every replica built from now on
  serves them), then each old replica is rolled: grow a fresh replica
  (new weights) + widen the batcher's runner pool, ``drain`` the old
  one, poll ``batcher.inflight`` to zero, ``retire`` it — the same
  drain-then-retire barrier the autoscaler's scale-down uses, which is
  exactly why in-flight requests never die during a swap. The bracket
  is journaled ``hot_swap_begin`` / ``hot_swap_done``; the report
  carries the ``failed`` delta across the swap window so callers can
  gate on *zero failed during swap* plus conservation.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from parallel_cnn_tpu import obs as obs_lib
from parallel_cnn_tpu.resilience.retry import RetryPolicy, retry_call
from parallel_cnn_tpu.serve.net import NetServer


class Supervisor:
    """Respawn a killed NetServer on its original port with bounded
    backoff.

    ``factory(port, seq_start) -> NetServer`` must return a *started*
    endpoint bound to ``port`` (0 on the first spawn picks an ephemeral
    port; every respawn passes the concrete port back so the address is
    stable across restarts). ``seq_start`` is the killed endpoint's
    wire-sequence watermark — the replacement continues the numbering,
    so a one-shot chaos schedule can't re-fire in the new incarnation.
    The factory should close over the shared WireStats and hand it to
    each incarnation.

    ``enabled=False`` builds the no-recovery control arm: the endpoint
    stays dead, clients exhaust their retries, and the scenario gate
    trips — the anti-vacuity proof that supervision is load-bearing.
    """

    def __init__(
        self,
        factory: Callable[[int, int], NetServer],
        *,
        policy: Optional[RetryPolicy] = None,
        obs: Optional["obs_lib.Obs"] = None,
        enabled: bool = True,
        port: int = 0,
        poll_interval_s: float = 0.005,
    ):
        self.factory = factory
        self.policy = policy or RetryPolicy(
            attempts=4, base_delay=0.05, max_delay=1.0, seed=0,
        )
        self.obs = obs if obs is not None else obs_lib.NOOP
        self.enabled = enabled
        self.poll_interval_s = poll_interval_s
        self._port_pref = port
        self._lock = threading.Lock()
        self._server: Optional[NetServer] = None
        self._closing = False
        self._respawns = 0
        self._gave_up = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Supervisor":
        srv = self.factory(self._port_pref, 0)
        thread = threading.Thread(
            target=self._monitor, name="serve-supervisor", daemon=True,
        )
        with self._lock:
            self._server = srv
            self._thread = thread
        thread.start()
        return self

    @property
    def server(self) -> Optional[NetServer]:
        with self._lock:
            return self._server

    @property
    def address(self) -> Tuple[str, int]:
        srv = self.server
        if srv is None:
            raise RuntimeError("supervisor not started")
        return srv.address

    @property
    def respawns(self) -> int:
        with self._lock:
            return self._respawns

    @property
    def gave_up(self) -> bool:
        """True when a respawn exhausted its retry budget — the bounded
        failure mode (supervision never loops forever)."""
        with self._lock:
            return self._gave_up

    def close(self) -> None:
        with self._lock:
            self._closing = True
            srv = self._server
        if srv is not None:
            srv.close()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5)

    def __enter__(self) -> "Supervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- the watch loop --------------------------------------------------

    def _monitor(self) -> None:
        while True:
            with self._lock:
                if self._closing:
                    return
                srv = self._server
            if srv is not None and srv.killed:
                if not self.enabled:
                    # Control arm: observe the death, recover nothing.
                    return
                if not self._respawn(srv):
                    return
            time.sleep(self.poll_interval_s)

    def _respawn(self, dead: NetServer) -> bool:
        t0 = time.monotonic()
        port = dead.port  # same address across incarnations
        seq_start = dead.next_seq()
        try:
            fresh = retry_call(
                self.factory, port, seq_start,
                policy=self.policy.decorrelated(self._respawns),
                retry_on=(OSError,),
                describe=f"respawn endpoint :{port}",
            )
        except OSError:
            with self._lock:
                self._gave_up = True
            if self.obs.enabled:
                self.obs.event(
                    "endpoint_respawn_gave_up", port=port,
                    attempts=self.policy.attempts,
                )
            return False
        downtime_ms = (time.monotonic() - t0) * 1e3
        with self._lock:
            self._server = fresh
            self._respawns += 1
            n = self._respawns
        if self.obs.enabled:
            self.obs.event(
                "endpoint_respawned", port=fresh.port, respawns=n,
                downtime_ms=downtime_ms, seq_start=seq_start,
            )
        return True


def hot_swap(
    pool,
    batcher,
    params: Any,
    model_state: Any = None,
    *,
    obs: Optional["obs_lib.Obs"] = None,
    drain_timeout_s: float = 10.0,
    poll_interval_s: float = 0.002,
) -> Dict[str, Any]:
    """Roll the pool onto new weights with zero downtime and zero failed
    requests.

    Sequence (per old replica, one at a time so capacity never dips):
    grow a fresh replica — which builds from the *new* host-side
    weights installed via ``pool.set_weights`` — widen the batcher's
    runner pool to match, then drain → poll in-flight to zero → retire
    the old one. A drain that never empties within ``drain_timeout_s``
    is un-drained (the replica returns to rotation, still on old
    weights) and reported rather than force-killed: a stuck swap must
    not become the outage it was avoiding.

    Returns a report dict: ``swapped`` / ``stuck`` slot lists, ``grown``
    slots, wall-clock ``seconds``, and ``failed_delta`` — the change in
    the batcher's ``failed`` counter across the swap window, which the
    scenario gate requires to be exactly 0.
    """
    obs = obs if obs is not None else obs_lib.NOOP
    t0 = time.monotonic()
    old = pool.routable()
    before = batcher.stats.snapshot()
    if obs.enabled:
        obs.event("hot_swap_begin", old_replicas=old)
    pool.set_weights(params, model_state)
    grown: List[int] = []
    swapped: List[int] = []
    stuck: List[int] = []
    for victim in old:
        fresh = pool.grow()
        grown.append(fresh)
        while pool.n_replicas > batcher.n_runners:
            batcher.add_runner()
        pool.drain(victim)
        deadline = time.monotonic() + drain_timeout_s
        while batcher.inflight(victim) > 0:
            if time.monotonic() > deadline:
                break
            time.sleep(poll_interval_s)
        if batcher.inflight(victim) > 0:
            pool.undrain(victim)
            stuck.append(victim)
            continue
        pool.retire(victim)
        swapped.append(victim)
    after = batcher.stats.snapshot()
    report = {
        "old": old,
        "grown": grown,
        "swapped": swapped,
        "stuck": stuck,
        "seconds": time.monotonic() - t0,
        "failed_delta": after["failed"] - before["failed"],
    }
    if obs.enabled:
        obs.event("hot_swap_done", **report)
    return report
