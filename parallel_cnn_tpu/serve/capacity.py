"""Predictive capacity planning: chosen serve plan + admission EWMAs →
replicas-needed, the feed-forward half of the autoscaler.

The reactive loop (serve/autoscaler.py) waits for a *symptom* — windowed
p99 over the SLO or a shed — and then pays hysteresis ticks before it
acts.  Under a flash crowd that is exactly one cooldown too late: the
queue fills, requests shed, and only then does capacity grow.  The
capacity model closes the loop one step earlier by predicting demand
from signals the serving stack already maintains:

- **Arrival rate** λ — the AdmissionController's interarrival EWMA
  (``arrival_rate``), fed by every submit (offered load, so demand is
  visible even while requests are being shed).
- **Per-replica service rate** μ — the chosen serve plan's batch bucket
  divided by that bucket's EWMA device time (``observe_service``
  feedback).  One replica running ``max_batch``-sized batches
  back-to-back completes ``max_batch / service_s`` requests per second;
  smaller observed buckets give proportionally smaller μ, and the
  planner uses the *best* observed bucket (the steady-state shape under
  load) rather than the pessimistic one admission uses for deadlines.
- **Headroom** — utilisation above ``headroom`` (default 0.6) leaves no
  slack for batch-formation gaps and queue draining, so the planner
  sizes for ``λ / (μ · headroom)`` replicas, the classic M/M/c-style
  occupancy guard band.

``replicas_needed`` returns ``None`` while either estimate is cold (no
arrivals yet, or no batch executed yet) — a prediction from nothing is
noise, so the autoscaler falls back to the reactive classifier until
the EWMAs warm up.  The model holds no lock and keeps no state of its
own: it is a pure read of the admission controller's estimators, cheap
enough to evaluate every autoscaler tick.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

from parallel_cnn_tpu.serve.admission import AdmissionController


class CapacityModel:
    """Replicas-needed from offered load and per-replica throughput.

    ``max_batch`` is the chosen serve plan's batch bucket (the
    ``DynamicBatcher`` cap — plan_to_configs on the serving side);
    ``headroom`` is the target peak utilisation per replica.
    """

    def __init__(
        self,
        admission: AdmissionController,
        *,
        max_batch: int,
        headroom: float = 0.6,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if not 0.0 < headroom <= 1.0:
            raise ValueError(f"headroom must be in (0, 1], got {headroom}")
        self.admission = admission
        self.max_batch = max_batch
        self.headroom = headroom

    # -- the two rates ---------------------------------------------------

    def arrival_rate(self) -> float:
        """Offered load λ in requests/s (0.0 while cold)."""
        return self.admission.arrival_rate()

    def service_rate(self) -> float:
        """Per-replica throughput μ in requests/s: the best observed
        bucket's ``bucket / service_ewma`` (0.0 while cold).  Buckets
        above ``max_batch`` are ignored — the ladder may have capped the
        effective bucket below what was once observed."""
        snap = self.admission.snapshot()
        best = 0.0
        for bucket, service_ms in snap["service_ewma_ms"].items():
            if bucket > self.max_batch or service_ms <= 0:
                continue
            best = max(best, bucket / (service_ms / 1e3))
        return best

    # -- the verdict -----------------------------------------------------

    def replicas_needed(self) -> Optional[int]:
        """ceil(λ / (μ · headroom)), or ``None`` while either estimate
        is cold (the autoscaler then stays purely reactive)."""
        lam = self.arrival_rate()
        mu = self.service_rate()
        if lam <= 0.0 or mu <= 0.0:
            return None
        return max(1, math.ceil(lam / (mu * self.headroom)))

    def snapshot(self) -> Dict[str, Any]:
        """Planner state for the metrics registry / bench artifacts."""
        return {
            "arrival_rate_rps": round(self.arrival_rate(), 3),
            "service_rate_rps": round(self.service_rate(), 3),
            "max_batch": self.max_batch,
            "headroom": self.headroom,
            "replicas_needed": self.replicas_needed(),
        }

    def attach_registry(self, registry, prefix: str = "capacity") -> None:
        """Expose the planner through an obs.MetricsRegistry (same
        pull-collector convention as the rest of the serving stack)."""
        registry.attach(prefix, self.snapshot)
