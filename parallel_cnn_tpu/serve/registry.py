"""Model registry: name → a uniform inference handle.

The trainers speak two dialects — the reference-parity LeNet is a bare
params pytree with a functional forward (models/lenet_ref + ops/reference),
the zoo models are nn.core.Module values with (params, model_state) and an
`apply`. Serving wants neither distinction: the engine needs exactly
``init(key) -> (params, model_state)`` and
``forward(params, model_state, x) -> outputs`` plus the per-sample input
shape, so every registered model is wrapped into that shape here.

Registered names match the CLI's --model choices, so any checkpoint the
trainers produce has a handle that can serve it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Tuple


@dataclasses.dataclass(frozen=True)
class ModelHandle:
    """Uniform inference surface over one model family member.

    - ``init(key) -> (params, model_state)`` — fresh weights, and the
      restore TEMPLATE for checkpoint loading (leaf shapes/dtypes).
    - ``forward(params, model_state, x) -> y`` — eval-mode batched
      forward ((n, *in_shape) → (n, n_outputs)); pure and jit/AOT-safe.
    - ``in_shape`` — per-sample input shape (no batch dim).
    """

    name: str
    in_shape: Tuple[int, ...]
    n_outputs: int
    init: Callable[[Any], Tuple[Any, Any]]
    forward: Callable[[Any, Any, Any], Any]


def _lenet_handle() -> ModelHandle:
    import jax

    from parallel_cnn_tpu.models import lenet_ref
    from parallel_cnn_tpu.ops import reference as ops

    def init(key):
        return lenet_ref.init(key), {}

    def forward(params, state, x):
        del state  # stateless model; uniform signature
        return jax.vmap(lambda s: ops.forward(params, s).out_f)(x)

    return ModelHandle("lenet_ref", (28, 28), 10, init, forward)


def _zoo_handle(name: str, factory, in_shape, n_outputs) -> ModelHandle:
    model = factory()

    def init(key):
        params, state, _ = model.init(key, in_shape)
        return params, state

    def forward(params, state, x):
        # train=False: BatchNorm evaluates from running stats — the
        # folded per-channel scale/shift form — and conv_backend="pallas"
        # layers take the fused single-kernel epilogue path
        # (nn/layers.py ConvBNAct).
        return model.apply(params, state, x, train=False)[0]

    return ModelHandle(name, in_shape, n_outputs, init, forward)


def available() -> Tuple[str, ...]:
    return ("lenet_ref", "cifar_cnn", "resnet18", "resnet34", "resnet50",
            "vgg16")


def get(name: str, conv_backend: str = "xla") -> ModelHandle:
    """Handle for a registered model name.

    ``conv_backend`` applies to the resnet/vgg families (same rule as
    the training CLI); other names require the default "xla".
    """
    if name == "lenet_ref":
        if conv_backend != "xla":
            raise ValueError(
                "conv_backend='pallas' applies to the resnet/vgg models"
            )
        return _lenet_handle()

    from parallel_cnn_tpu.nn import cifar, resnet, vgg

    zoo: Dict[str, Tuple[Callable, Tuple[int, ...], int]] = {
        "cifar_cnn": (lambda: cifar.cifar_cnn(), cifar.IN_SHAPE, 10),
        "resnet18": (lambda: resnet.resnet18(
            10, cifar_stem=True, conv_backend=conv_backend
        ), cifar.IN_SHAPE, 10),
        "resnet34": (lambda: resnet.resnet34(
            10, cifar_stem=True, conv_backend=conv_backend
        ), cifar.IN_SHAPE, 10),
        "resnet50": (lambda: resnet.resnet50(
            10, cifar_stem=True, conv_backend=conv_backend
        ), cifar.IN_SHAPE, 10),
        "vgg16": (lambda: vgg.vgg16(10, conv_backend=conv_backend),
                  cifar.IN_SHAPE, 10),
    }
    if name not in zoo:
        raise KeyError(
            f"unknown model {name!r}; registered: {', '.join(available())}"
        )
    if name == "cifar_cnn" and conv_backend != "xla":
        raise ValueError(
            "conv_backend='pallas' applies to the resnet/vgg models"
        )
    factory, in_shape, n_out = zoo[name]
    return _zoo_handle(name, factory, in_shape, n_out)
