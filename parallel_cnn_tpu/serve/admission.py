"""SLO admission control: reject-early shedding and the graceful
degradation ladder in front of the dynamic batcher.

Why a controller in front of a bounded queue that already sheds: the
queue sheds on *occupancy* — a request admitted into a deep backlog
still waits the whole backlog out, misses its deadline, and wastes a
queue slot (and possibly a device slot) producing an answer nobody
reads. The admission controller sheds on *prediction* instead:

- **EWMA estimators.** The batcher feeds back the queue wait of every
  dispatched batch (``observe_queue_wait``) and the device time of
  every executed bucket (``observe_service``). ``predicted_wait_s``
  combines them — the wait a request admitted *now* should expect.
- **Reject-early.** A request whose deadline would already be missed by
  the predicted completion time is rejected at submit
  (``Overloaded``, counted as a shed — conservation holds), freeing
  the client to retry elsewhere immediately instead of after a doomed
  queue wait.
- **Degradation ladder.** Queue pressure (fill fraction, hysteresis
  bands so the level does not flap) walks a 4-level ladder:

      L0 normal            everything admitted, full coalescing window
      L1 shrink-wait       coalescing window cut to 1/4 — latency first
      L2 cap-bucket        batch bucket halved — bound per-batch service
      L3 shed-best-effort  best-effort priority class rejected outright

  Every transition is journaled (``admission_level`` obs event) so a
  pressure excursion is reconstructable from the journal alone.

The controller is clock-injectable and lock-guarded; the batcher calls
``admit`` on the submit path and the effective-knob getters on the
worker path, so everything here must stay a few arithmetic ops.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from parallel_cnn_tpu import obs as obs_lib

#: Ladder level names, L0..L3 (index == level).
LEVELS = ("normal", "shrink-wait", "cap-bucket", "shed-best-effort")

#: Queue fill fraction at which level i+1 engages…
_UP = (0.50, 0.75, 0.90)
#: …and the fill fraction below which it releases (hysteresis band).
_DOWN = (0.30, 0.55, 0.70)


class AdmissionController:
    """Per-request admission verdicts + the degradation ladder.

    ``slo_ms`` is the default completion objective used when a request
    carries no deadline of its own; ``queue_depth`` must match the
    batcher's bound (fill fraction is the pressure signal).
    """

    def __init__(
        self,
        *,
        slo_ms: float = 100.0,
        queue_depth: int = 256,
        ewma_alpha: float = 0.3,
        obs: Optional["obs_lib.Obs"] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if slo_ms <= 0:
            raise ValueError(f"slo_ms must be > 0, got {slo_ms}")
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        if not 0.0 < ewma_alpha <= 1.0:
            raise ValueError(f"ewma_alpha must be in (0, 1], got {ewma_alpha}")
        self.slo_ms = slo_ms
        self.queue_depth = queue_depth
        self.ewma_alpha = ewma_alpha
        self.obs = obs if obs is not None else obs_lib.NOOP
        self._clock = clock
        self._lock = threading.Lock()
        self._level = 0
        self._queue_wait_ewma: Optional[float] = None   # seconds
        self._service_ewma: Dict[int, float] = {}       # bucket → seconds
        self._last_arrival: Optional[float] = None      # monotonic seconds
        self._interarrival_ewma: Optional[float] = None  # seconds between
        self._admitted = 0
        self._rejected_late = 0
        self._rejected_ladder = 0

    # -- estimator feedback (batcher worker/runner call these) ----------

    def observe_queue_wait(self, wait_s: float) -> None:
        """Batch-formation feedback: the longest queue wait in the batch
        just dispatched (the pessimistic end — admission should be)."""
        with self._lock:
            prev = self._queue_wait_ewma
            self._queue_wait_ewma = (
                wait_s if prev is None
                else prev + self.ewma_alpha * (wait_s - prev)
            )

    def observe_service(self, bucket: int, service_s: float) -> None:
        """Execution feedback: device time for one batch of ``bucket``."""
        with self._lock:
            prev = self._service_ewma.get(bucket)
            self._service_ewma[bucket] = (
                service_s if prev is None
                else prev + self.ewma_alpha * (service_s - prev)
            )

    def _observe_arrival(self, now: float) -> None:
        """Demand feedback: every submit (admitted OR shed — offered
        load is the signal, not carried load) updates the interarrival
        EWMA the capacity planner reads through ``arrival_rate``."""
        with self._lock:
            last = self._last_arrival
            self._last_arrival = now
            if last is None:
                return
            dt = max(now - last, 1e-6)  # same-tick bursts still count
            prev = self._interarrival_ewma
            self._interarrival_ewma = (
                dt if prev is None
                else prev + self.ewma_alpha * (dt - prev)
            )

    def arrival_rate(self) -> float:
        """Offered load in requests/s (1 / interarrival EWMA); 0.0 until
        two arrivals have been seen — a cold estimate predicts nothing,
        so the capacity planner falls back to the reactive loop."""
        with self._lock:
            ia = self._interarrival_ewma
            return 1.0 / ia if ia else 0.0

    def predicted_wait_s(self) -> float:
        """Expected submit→result time for a request admitted now:
        EWMA queue wait + the slowest bucket's EWMA service time (a new
        request may coalesce into any bucket; the pessimistic bound is
        what a deadline promise must survive). 0.0 until the first
        observations arrive — a cold controller admits everything."""
        with self._lock:
            wait = self._queue_wait_ewma or 0.0
            service = max(self._service_ewma.values(), default=0.0)
            return wait + service

    # -- ladder ---------------------------------------------------------

    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    @property
    def level_name(self) -> str:
        return LEVELS[self.level]

    def _update_level(self, queue_depth: int) -> int:
        """Walk the ladder one rung per call toward the fill fraction's
        band (hysteresis: the engage and release thresholds differ, so
        a fill hovering at one threshold cannot flap the level)."""
        fill = queue_depth / self.queue_depth
        with self._lock:
            old = self._level
            if old < len(_UP) and fill >= _UP[old]:
                self._level = old + 1
            elif old > 0 and fill < _DOWN[old - 1]:
                self._level = old - 1
            new = self._level
        if new != old and self.obs.enabled:
            self.obs.event(
                "admission_level",
                old=LEVELS[old], new=LEVELS[new],
                fill=round(fill, 3),
            )
        return new

    def effective_wait_s(self, base_s: float) -> float:
        """Coalescing window under the ladder: L1+ cuts it to 1/4 —
        under pressure, stop waiting for stragglers to fill buckets."""
        return base_s / 4.0 if self.level >= 1 else base_s

    def effective_max_batch(self, base: int) -> int:
        """Bucket cap under the ladder: L2+ halves it — smaller batches
        bound the per-batch service time a queued request waits behind."""
        return max(1, base // 2) if self.level >= 2 else base

    # -- the verdict ----------------------------------------------------

    def admit(
        self,
        *,
        priority: str,
        deadline: Optional[float],
        now: Optional[float] = None,
        queue_depth: int = 0,
    ) -> Optional[str]:
        """None to admit, else the rejection reason (the batcher raises
        it as ``Overloaded`` and counts a shed).

        ``deadline`` is absolute monotonic seconds (None → the
        controller's own slo_ms budget is the objective)."""
        now = self._clock() if now is None else now
        self._observe_arrival(now)
        level = self._update_level(queue_depth)
        if level >= 3 and priority == "best-effort":
            with self._lock:
                self._rejected_ladder += 1
            return (
                f"degradation level {LEVELS[level]} sheds "
                "best-effort traffic"
            )
        predicted = self.predicted_wait_s()
        budget = (
            deadline - now if deadline is not None else self.slo_ms / 1e3
        )
        if predicted > budget:
            with self._lock:
                self._rejected_late += 1
            return (
                f"predicted completion {1e3 * predicted:.1f} ms exceeds "
                f"the {1e3 * budget:.1f} ms budget"
            )
        with self._lock:
            self._admitted += 1
        return None

    def snapshot(self) -> Dict[str, Any]:
        """Controller state for the metrics registry / debugging."""
        with self._lock:
            return {
                "level": self._level,
                "level_name": LEVELS[self._level],
                "admitted": self._admitted,
                "rejected_late": self._rejected_late,
                "rejected_ladder": self._rejected_ladder,
                "queue_wait_ewma_ms": (
                    1e3 * self._queue_wait_ewma
                    if self._queue_wait_ewma is not None else None
                ),
                "service_ewma_ms": {
                    b: 1e3 * s for b, s in self._service_ewma.items()
                },
                "arrival_rate_rps": (
                    1.0 / self._interarrival_ewma
                    if self._interarrival_ewma else 0.0
                ),
            }

    def attach_registry(self, registry, prefix: str = "admission") -> None:
        """Expose the controller through an obs.MetricsRegistry (same
        pull-collector convention as ServeStats.attach_registry)."""
        registry.attach(prefix, self.snapshot)
