"""Serving telemetry: one ServeStats object shared by engine, batcher,
and CLI — per-request latency percentiles (utils.metrics.Histogram),
queue depth, batch occupancy, and shed/expiry rates.

Two time horizons per signal:

- **lifetime** counters (``submitted``, ``shed_rate()``, ``snapshot()``
  …) — the conservation-law view tests and the CLI epilogue pin; keys
  and semantics are frozen.
- **windowed** views (``window_shed_rate()``, ``window_occupancy()``,
  ``window_p99_ms()``, ``window_snapshot()``) — the same signals under
  an exponential decay with time constant ``window_s``, so a control
  loop (serve/autoscaler.py) reacts to the last few seconds of load
  instead of the run's lifetime average. An event recorded ``window_s``
  seconds ago carries weight 1/e.

Everything here is host-side counters around the device work, so the
cost per request is a few lock acquisitions — nothing touches jax.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, Optional

from parallel_cnn_tpu.utils.metrics import Histogram


class _DecayingCounter:
    """Float counter whose mass decays exp(-(now - t_last)/tau). NOT
    thread-safe — callers hold the owning ServeStats lock."""

    __slots__ = ("tau", "value", "t_last")

    def __init__(self, tau: float):
        self.tau = tau
        self.value = 0.0
        self.t_last: Optional[float] = None

    def _decay_to(self, now: float) -> None:
        if self.t_last is not None and now > self.t_last:
            self.value *= math.exp((self.t_last - now) / self.tau)
        self.t_last = now

    def add(self, x: float, now: float) -> None:
        self._decay_to(now)
        self.value += x

    def read(self, now: float) -> float:
        self._decay_to(now)
        return self.value


class _DecayingHistogram:
    """Log-binned histogram with exponentially decayed float counts —
    the windowed twin of utils.metrics.Histogram (same bin geometry,
    recent samples dominate the percentile). NOT thread-safe — callers
    hold the owning ServeStats lock."""

    def __init__(self, tau: float, lo: float = 1e-5, hi: float = 100.0,
                 bins: int = 96):
        self.tau = tau
        self.lo = lo
        self.hi = hi
        self.bins = bins
        self._ratio = math.log(hi / lo)
        self._counts = [0.0] * bins
        self._t_last: Optional[float] = None

    def _decay_to(self, now: float) -> None:
        if self._t_last is not None and now > self._t_last:
            f = math.exp((self._t_last - now) / self.tau)
            self._counts = [c * f for c in self._counts]
        self._t_last = now

    def record(self, x: float, now: float) -> None:
        self._decay_to(now)
        if x <= self.lo:
            i = 0
        elif x >= self.hi:
            i = self.bins - 1
        else:
            i = min(self.bins - 1,
                    int(self.bins * math.log(x / self.lo) / self._ratio))
        self._counts[i] += 1.0

    def percentile(self, p: float, now: float) -> Optional[float]:
        """Geometric bin-midpoint percentile over the decayed mass;
        None once less than half a sample's weight survives — a stale
        percentile must go silent, not linger at its last value."""
        self._decay_to(now)
        total = sum(self._counts)
        if total < 0.5:
            return None
        target = total * p / 100.0
        acc = 0.0
        for i, c in enumerate(self._counts):
            acc += c
            if acc >= target:
                lo_e = self.lo * math.exp(self._ratio * i / self.bins)
                hi_e = self.lo * math.exp(self._ratio * (i + 1) / self.bins)
                return math.sqrt(lo_e * hi_e)
        return self.hi


class WireStats:
    """Wire-tier request accounting for the network front door
    (serve/net.py) — the same conservation law as :class:`ServeStats`,
    one boundary further out: every request *observed on the socket*
    resolves exactly once as completed (reply written), shed (typed
    Overloaded reply), expired (typed DeadlineExceeded reply, or a
    stalled/half-read socket reaped at the connection deadline), or
    failed (endpoint death with the request in flight, or an error
    reply). ``submitted == completed + shed + expired + failed`` must
    therefore hold over the wire in every scenario — including across a
    ``kill-endpoint@`` respawn, where the in-flight remainder lands in
    ``failed`` rather than vanishing.

    Deliberately a separate object from the batcher's ServeStats: a
    slow-loris request that never finished arriving was never
    ``submit()``-ed to the batcher, so it exists only at this tier, and
    an endpoint death fails the wire view of a request the batcher may
    still complete internally. Thread-safe; shared across endpoint
    respawns so the law spans restarts."""

    def __init__(self):
        self._lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.shed = 0
        self.expired = 0
        self.failed = 0
        # Connection-level context (not part of the conservation sum).
        self.conn_opened = 0
        self.conn_closed = 0
        self.reaped = 0          # expired subset: stalled sockets reaped
        self.endpoint_deaths = 0

    def on_submit(self) -> None:
        with self._lock:
            self.submitted += 1

    def on_complete(self) -> None:
        with self._lock:
            self.completed += 1

    def on_shed(self) -> None:
        with self._lock:
            self.shed += 1

    def on_expired(self, n: int = 1, reaped: bool = False) -> None:
        with self._lock:
            self.expired += n
            if reaped:
                self.reaped += n

    def on_failed(self, n: int = 1) -> None:
        with self._lock:
            self.failed += n

    def on_conn_open(self) -> None:
        with self._lock:
            self.conn_opened += 1

    def on_conn_close(self) -> None:
        with self._lock:
            self.conn_closed += 1

    def on_endpoint_death(self) -> None:
        with self._lock:
            self.endpoint_deaths += 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "shed": self.shed,
                "expired": self.expired,
                "failed": self.failed,
                "conn_opened": self.conn_opened,
                "conn_closed": self.conn_closed,
                "reaped": self.reaped,
                "endpoint_deaths": self.endpoint_deaths,
            }

    def balanced(self) -> bool:
        """The wire conservation law, as a predicate."""
        with self._lock:
            return self.submitted == (
                self.completed + self.shed + self.expired + self.failed
            )

    def attach_registry(self, registry, prefix: str = "wire") -> None:
        """Expose through an obs.MetricsRegistry (same pull-collector
        convention as ServeStats)."""
        registry.attach(prefix, self.snapshot)


class ServeStats:
    """Aggregated serving counters. Thread-safe.

    ``window_s`` is the exponential-decay time constant for the windowed
    views; ``clock`` is injectable (monotonic seconds) so control-loop
    tests can drive the decay deterministically."""

    def __init__(self, window_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self._lock = threading.Lock()
        self._clock = clock
        self.window_s = window_s
        # End-to-end request latency (submit → result ready), seconds.
        self.latency = Histogram(1e-5, 100.0, bins=96)
        self.submitted = 0
        self.completed = 0
        self.shed = 0        # rejected at submit: queue full / admission
        self.expired = 0     # dropped at coalesce/dispatch: deadline passed
        self.failed = 0      # engine-side errors propagated to futures
        self.batches = 0
        self.requests_in_batches = 0
        self.padded_slots = 0       # bucket − occupancy, summed
        self.queue_depth_sum = 0
        self.queue_depth_max = 0
        self.replica_batches: Dict[int, int] = {}
        # Windowed (decayed) twins of the control-relevant signals.
        self._w_submitted = _DecayingCounter(window_s)
        self._w_shed = _DecayingCounter(window_s)
        self._w_requests = _DecayingCounter(window_s)
        self._w_padded = _DecayingCounter(window_s)
        self._w_latency = _DecayingHistogram(window_s)

    # -- recording hooks (batcher/engine call these) --------------------

    def on_submit(self) -> None:
        with self._lock:
            self.submitted += 1
            self._w_submitted.add(1.0, self._clock())

    def on_shed(self) -> None:
        with self._lock:
            self.shed += 1
            self._w_shed.add(1.0, self._clock())

    def on_expired(self, n: int = 1) -> None:
        with self._lock:
            self.expired += n

    def on_batch(self, n: int, bucket: int, replica: int,
                 queue_depth: int) -> None:
        with self._lock:
            self.batches += 1
            self.requests_in_batches += n
            self.padded_slots += bucket - n
            self.queue_depth_sum += queue_depth
            self.queue_depth_max = max(self.queue_depth_max, queue_depth)
            self.replica_batches[replica] = (
                self.replica_batches.get(replica, 0) + 1
            )
            now = self._clock()
            self._w_requests.add(float(n), now)
            self._w_padded.add(float(bucket - n), now)

    def on_complete(self, latency_s: float) -> None:
        self.latency.record(latency_s)
        with self._lock:
            self.completed += 1
            self._w_latency.record(latency_s, self._clock())

    def on_failed(self, n: int = 1) -> None:
        with self._lock:
            self.failed += n

    # -- views ----------------------------------------------------------

    def shed_rate(self) -> float:
        with self._lock:
            return self.shed / self.submitted if self.submitted else 0.0

    def mean_occupancy(self) -> Optional[float]:
        """Mean fraction of dispatched batch slots holding real requests
        (padding is the waste dynamic bucketing pays for shape reuse)."""
        with self._lock:
            total = self.requests_in_batches + self.padded_slots
            return self.requests_in_batches / total if total else None

    # -- windowed views (the autoscaler's control inputs) ---------------

    def window_shed_rate(self) -> float:
        """Shed fraction over the decay window (0.0 when the window is
        empty — an idle server is not overloaded). "Empty" is less than
        half a request of surviving mass: the shed/submitted *ratio*
        does not decay (both masses shrink by the same factor), so
        without the idle cutoff a long-past shed burst would read as an
        overload forever and wedge the autoscaler's scale-down path."""
        with self._lock:
            now = self._clock()
            sub = self._w_submitted.read(now)
            return self._w_shed.read(now) / sub if sub >= 0.5 else 0.0

    def window_occupancy(self) -> Optional[float]:
        """Batch occupancy over the decay window; None when no batch
        dispatched recently (idle — a scale-down signal of its own).
        Same half-a-request idle cutoff as ``window_shed_rate``."""
        with self._lock:
            now = self._clock()
            req = self._w_requests.read(now)
            total = req + self._w_padded.read(now)
            return req / total if total >= 0.5 else None

    def window_p99_ms(self) -> Optional[float]:
        """p99 end-to-end latency (ms) over the decay window; None when
        no request completed recently."""
        with self._lock:
            p = self._w_latency.percentile(99.0, self._clock())
            return p * 1e3 if p is not None else None

    def window_snapshot(self) -> Dict[str, Any]:
        """The windowed signals in one dict (separate from ``snapshot``
        on purpose — its lifetime keys are a frozen contract)."""
        return {
            "window_s": self.window_s,
            "shed_rate": self.window_shed_rate(),
            "occupancy": self.window_occupancy(),
            "p99_ms": self.window_p99_ms(),
        }

    def snapshot(self) -> Dict[str, Any]:
        lat = self.latency.summary(scale=1e3)  # ms
        with self._lock:
            snap: Dict[str, Any] = {
                "submitted": self.submitted,
                "completed": self.completed,
                "shed": self.shed,
                "expired": self.expired,
                "failed": self.failed,
                "batches": self.batches,
                "queue_depth_mean": (
                    self.queue_depth_sum / self.batches if self.batches
                    else 0.0
                ),
                "queue_depth_max": self.queue_depth_max,
                "replica_batches": dict(self.replica_batches),
            }
        snap["shed_rate"] = self.shed_rate()
        occ = self.mean_occupancy()
        snap["batch_occupancy"] = occ if occ is not None else 0.0
        snap["latency_ms"] = lat
        return snap

    def attach_registry(self, registry, prefix: str = "serve") -> None:
        """Expose this ServeStats through an obs.MetricsRegistry.

        Registers ``snapshot`` as a collector, so every exposition
        (Prometheus text or JSON) pulls the live counters under
        ``<prefix>.*`` — the counters themselves keep their semantics
        and locking; the registry never caches them."""
        registry.attach(prefix, self.snapshot)

    def render(self) -> str:
        """Human-readable one-screen summary (the CLI's epilogue)."""
        s = self.snapshot()
        lat = s["latency_ms"]
        lines = [
            f"requests: {s['submitted']} submitted, {s['completed']} ok, "
            f"{s['shed']} shed, {s['expired']} expired, {s['failed']} failed",
            f"batches:  {s['batches']} "
            f"(occupancy {s['batch_occupancy']:.2f}, "
            f"queue depth mean {s['queue_depth_mean']:.1f} "
            f"max {s['queue_depth_max']})",
        ]
        if lat.get("count"):
            lines.append(
                f"latency:  p50 {lat['p50']:.2f} ms, p90 {lat['p90']:.2f} ms, "
                f"p99 {lat['p99']:.2f} ms (mean {lat['mean']:.2f}, "
                f"max {lat['max']:.2f})"
            )
        if s["replica_batches"]:
            per = ", ".join(
                f"r{i}: {n}" for i, n in sorted(s["replica_batches"].items())
            )
            lines.append(f"replicas: {per}")
        return "\n".join(lines)
