"""Serving telemetry: one ServeStats object shared by engine, batcher,
and CLI — per-request latency percentiles (utils.metrics.Histogram),
queue depth, batch occupancy, and shed/expiry rates.

Everything here is host-side counters around the device work, so the
cost per request is a few lock acquisitions — nothing touches jax.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from parallel_cnn_tpu.utils.metrics import Histogram


class ServeStats:
    """Aggregated serving counters. Thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        # End-to-end request latency (submit → result ready), seconds.
        self.latency = Histogram(1e-5, 100.0, bins=96)
        self.submitted = 0
        self.completed = 0
        self.shed = 0        # rejected at submit: bounded queue full
        self.expired = 0     # dropped at dispatch: deadline passed
        self.failed = 0      # engine-side errors propagated to futures
        self.batches = 0
        self.requests_in_batches = 0
        self.padded_slots = 0       # bucket − occupancy, summed
        self.queue_depth_sum = 0
        self.queue_depth_max = 0
        self.replica_batches: Dict[int, int] = {}

    # -- recording hooks (batcher/engine call these) --------------------

    def on_submit(self) -> None:
        with self._lock:
            self.submitted += 1

    def on_shed(self) -> None:
        with self._lock:
            self.shed += 1

    def on_expired(self, n: int = 1) -> None:
        with self._lock:
            self.expired += n

    def on_batch(self, n: int, bucket: int, replica: int,
                 queue_depth: int) -> None:
        with self._lock:
            self.batches += 1
            self.requests_in_batches += n
            self.padded_slots += bucket - n
            self.queue_depth_sum += queue_depth
            self.queue_depth_max = max(self.queue_depth_max, queue_depth)
            self.replica_batches[replica] = (
                self.replica_batches.get(replica, 0) + 1
            )

    def on_complete(self, latency_s: float) -> None:
        self.latency.record(latency_s)
        with self._lock:
            self.completed += 1

    def on_failed(self, n: int = 1) -> None:
        with self._lock:
            self.failed += n

    # -- views ----------------------------------------------------------

    def shed_rate(self) -> float:
        with self._lock:
            return self.shed / self.submitted if self.submitted else 0.0

    def mean_occupancy(self) -> Optional[float]:
        """Mean fraction of dispatched batch slots holding real requests
        (padding is the waste dynamic bucketing pays for shape reuse)."""
        with self._lock:
            total = self.requests_in_batches + self.padded_slots
            return self.requests_in_batches / total if total else None

    def snapshot(self) -> Dict[str, Any]:
        lat = self.latency.summary(scale=1e3)  # ms
        with self._lock:
            snap: Dict[str, Any] = {
                "submitted": self.submitted,
                "completed": self.completed,
                "shed": self.shed,
                "expired": self.expired,
                "failed": self.failed,
                "batches": self.batches,
                "queue_depth_mean": (
                    self.queue_depth_sum / self.batches if self.batches
                    else 0.0
                ),
                "queue_depth_max": self.queue_depth_max,
                "replica_batches": dict(self.replica_batches),
            }
        snap["shed_rate"] = self.shed_rate()
        occ = self.mean_occupancy()
        snap["batch_occupancy"] = occ if occ is not None else 0.0
        snap["latency_ms"] = lat
        return snap

    def attach_registry(self, registry, prefix: str = "serve") -> None:
        """Expose this ServeStats through an obs.MetricsRegistry.

        Registers ``snapshot`` as a collector, so every exposition
        (Prometheus text or JSON) pulls the live counters under
        ``<prefix>.*`` — the counters themselves keep their semantics
        and locking; the registry never caches them."""
        registry.attach(prefix, self.snapshot)

    def render(self) -> str:
        """Human-readable one-screen summary (the CLI's epilogue)."""
        s = self.snapshot()
        lat = s["latency_ms"]
        lines = [
            f"requests: {s['submitted']} submitted, {s['completed']} ok, "
            f"{s['shed']} shed, {s['expired']} expired, {s['failed']} failed",
            f"batches:  {s['batches']} "
            f"(occupancy {s['batch_occupancy']:.2f}, "
            f"queue depth mean {s['queue_depth_mean']:.1f} "
            f"max {s['queue_depth_max']})",
        ]
        if lat.get("count"):
            lines.append(
                f"latency:  p50 {lat['p50']:.2f} ms, p90 {lat['p90']:.2f} ms, "
                f"p99 {lat['p99']:.2f} ms (mean {lat['mean']:.2f}, "
                f"max {lat['max']:.2f})"
            )
        if s["replica_batches"]:
            per = ", ".join(
                f"r{i}: {n}" for i, n in sorted(s["replica_batches"].items())
            )
            lines.append(f"replicas: {per}")
        return "\n".join(lines)
