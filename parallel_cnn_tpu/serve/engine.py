"""Inference engine: checkpoint → AOT-compiled per-bucket predict.

Design (tentpole of the serve/ subsystem):

- **Restore** goes through train/checkpoint.load_params with the handle's
  fresh init as the leaf-validated template; zoo checkpoints (full
  ZooState) restore params + BN running stats and IGNORE the optimizer
  momentum (``opt_state={}`` contributes no leaves — see load_params).
- **BN folds at compile time**: the engine closes its predict function
  over the params/model_state arrays, so inside the traced graph they are
  constants — XLA constant-folds the eval-mode BatchNorm's
  ``rsqrt(var+eps)*scale`` per-channel fold (and everything else that
  depends only on weights) once per bucket, instead of recomputing it on
  every request.
- **AOT per shape bucket**: requests pad into the nearest power-of-two
  batch bucket (1, 2, 4, …, max_batch) and each bucket is compiled ONCE
  via ``jax.jit(...).lower(...).compile()``. Steady-state requests never
  trigger a trace: a new shape can only be a new bucket, and with
  ``precompile()`` not even that. The padding cost is bounded — a bucket
  is at most 2× its smallest occupant, so padded FLOPs are < 2× useful
  FLOPs worst-case (docs/serving.md for the amortized math).
- **Device pinning**: every executable is lowered for one explicit
  device, so ReplicaPool can pin n engine copies round-robin across local
  devices and run independent batches concurrently.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from parallel_cnn_tpu import obs as obs_lib


@dataclasses.dataclass
class EngineStats:
    """AOT compile-cache counters (tests pin the hit/miss accounting).

    ``aot_hits`` counts in-memory executable reuse on the predict path;
    the ``aot_cache_*`` trio counts the persistent on-disk tier
    (hit = executable deserialized instead of compiled, miss = no entry
    on disk, corrupt = an entry existed but was torn / bit-rotted /
    fingerprint-mismatched and fell back to recompile). All mutations
    happen under the owning Engine's lock."""

    aot_compiles: int = 0
    aot_hits: int = 0
    predicts: int = 0
    compile_seconds: Dict[int, float] = dataclasses.field(default_factory=dict)
    aot_cache_hits: int = 0
    aot_cache_misses: int = 0
    aot_cache_corrupt: int = 0


class AotCacheWarning(UserWarning):
    """A persistent AOT-cache entry could not be used — torn write, byte
    corruption, or a jax/XLA/weights fingerprint mismatch. The engine
    recompiles and overwrites the entry; this warning is the typed
    signal of the degraded path (same contract as checkpoint.restore's
    typed ValueError: loud, specific, never a crash)."""


class AotCacheError(RuntimeError):
    """Internal: one on-disk AOT cache entry is unusable (the message
    says why). Callers catch this, warn :class:`AotCacheWarning`, and
    recompile — it never escapes the engine."""


#: On-disk entry magic; bump the suffix when the layout changes so an
#: old-layout entry reads as a typed mismatch, not a pickle crash.
_AOT_MAGIC = b"PCNN-AOT1\n"


class ReplicaDead(RuntimeError):
    """A replica is gone — killed by chaos (``kill-replica@SEQ``) or
    evicted after a real device failure. Carries the replica index so the
    batcher's failover path can evict/respawn exactly the dead one and
    retry the in-flight batch on a survivor."""

    def __init__(self, replica: int, message: str = ""):
        super().__init__(message or f"replica {replica} is dead")
        self.replica = replica


def load_or_init(handle, checkpoint: Optional[str] = None, seed: int = 0):
    """(params, model_state) for a handle — restored from a checkpoint
    when given, else fresh-initialized from ``seed``.

    Accepts both checkpoint dialects: a bare params pytree (the
    reference-parity LeNet path) and a full zoo ZooState (params + BN
    stats + optimizer state; the optimizer leaves are ignored — an
    inference engine must not need to reconstruct the training-time
    optimizer just to read the weights)."""
    import jax

    params, model_state = handle.init(jax.random.key(seed))
    if checkpoint is None:
        return params, model_state
    from parallel_cnn_tpu.train import checkpoint as ckpt_lib

    from parallel_cnn_tpu.train.zoo import ZooState

    if jax.tree_util.tree_leaves(model_state):
        # Stateful model (BN running stats): only the ZooState dialect
        # can carry the state, so there is nothing to guess.
        template = ZooState(params, model_state, {})
        loaded = ckpt_lib.load_params(checkpoint, template)
        return loaded.params, loaded.model_state
    # Stateless model: the file may be a bare params pytree (the lenet
    # parity trainer's dialect) OR a full ZooState whose model_state is
    # empty (zoo.train always wraps). Key layout disambiguates — try
    # bare first, fall back to the wrapped template on a leaf-set miss.
    try:
        return ckpt_lib.load_params(checkpoint, params), model_state
    except ValueError as bare_err:
        try:
            loaded = ckpt_lib.load_params(
                checkpoint, ZooState(params, model_state, {})
            )
        except ValueError:
            raise bare_err from None
        return loaded.params, loaded.model_state


def params_digest(params: Any, model_state: Any) -> str:
    """Content hash of the weights an executable was compiled against.

    The engine closes predict over the params/model_state arrays, so the
    compiled executable *is* a function of their values — a persistent
    cache entry is only valid for the exact weights it was built from
    (the hot-swap path depends on this: new checkpoint → new digest →
    stale entries read as fingerprint mismatches, never as silently
    wrong answers)."""
    import jax

    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves((params, model_state)):
        a = np.asarray(leaf)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def bucket_for(n: int, max_batch: int) -> int:
    """Smallest power-of-two bucket holding n requests."""
    if n < 1:
        raise ValueError(f"need at least one request, got {n}")
    b = 1 << (n - 1).bit_length()
    if b > max_batch:
        raise ValueError(
            f"batch of {n} exceeds max_batch={max_batch}; split upstream"
        )
    return b


class Engine:
    """Single-replica engine: pad → AOT executable → unpad.

    Thread-safe: the compile cache is guarded, and concurrent predict()
    calls on already-compiled buckets go straight to the executable
    (jax dispatch is thread-safe).
    """

    def __init__(
        self,
        handle,
        *,
        params: Any = None,
        model_state: Any = None,
        checkpoint: Optional[str] = None,
        max_batch: int = 64,
        device=None,
        seed: int = 0,
        precompile: bool = False,
        obs: Optional["obs_lib.Obs"] = None,
        cache_dir: Optional[str] = None,
        plan_fingerprint: Optional[str] = None,
    ):
        import jax

        if max_batch < 1 or (max_batch & (max_batch - 1)):
            raise ValueError(
                f"max_batch must be a power of two >= 1, got {max_batch}"
            )
        self.handle = handle
        self.max_batch = max_batch
        self.obs = obs if obs is not None else obs_lib.NOOP
        self.device = device if device is not None else jax.devices()[0]
        if params is None:
            params, model_state = load_or_init(handle, checkpoint, seed)
        # Pin the weights to this replica's device once; the closures
        # below capture the pinned copies as trace-time constants.
        self._params = jax.device_put(params, self.device)
        self._state = jax.device_put(
            model_state if model_state is not None else {}, self.device
        )
        self.stats = EngineStats()
        self._exec: Dict[int, Any] = {}
        self._lock = threading.Lock()
        # Persistent on-disk AOT-executable tier: a respawned / grown /
        # cold-started replica deserializes its per-bucket executables
        # instead of recompiling. The fingerprint pins everything the
        # executable is a function of — an entry that does not match
        # EXACTLY falls back to recompile with a typed warning.
        self.cache_dir = cache_dir
        self.plan_fingerprint = plan_fingerprint
        self._cache_ok = cache_dir is not None
        if self._cache_ok:
            os.makedirs(cache_dir, exist_ok=True)
            self._fingerprint = {
                # The resolved ExecutionPlan's content fingerprint
                # (plan/): serving under a different plan (AOT policy,
                # eval sharding) must not reuse another plan's
                # executables.
                "plan": plan_fingerprint or "",
                "jax": jax.__version__,
                "backend": getattr(
                    getattr(self.device, "client", None),
                    "platform_version", "?",
                ),
                "platform": self.device.platform,
                "device_kind": getattr(self.device, "device_kind", "?"),
                "device": int(self.device.id),
                "model": handle.name,
                "in_shape": list(handle.in_shape),
                "params": params_digest(self._params, self._state),
            }
        if precompile:
            self.precompile()

    @property
    def buckets(self) -> List[int]:
        """The bucket ladder: 1, 2, 4, …, max_batch."""
        return [1 << i for i in range(self.max_batch.bit_length())]

    def bucket_for(self, n: int) -> int:
        return bucket_for(n, self.max_batch)

    def _compile(self, bucket: int):
        import jax
        import jax.numpy as jnp
        from jax.sharding import SingleDeviceSharding

        params, state, handle = self._params, self._state, self.handle

        def predict(x):
            return handle.forward(params, state, x)

        sds = jax.ShapeDtypeStruct(
            (bucket, *handle.in_shape), jnp.float32,
            sharding=SingleDeviceSharding(self.device),
        )
        t0 = time.perf_counter()
        with self.obs.span("serve.aot_compile", cat="serve", bucket=bucket):
            compiled = jax.jit(predict).lower(sds).compile()
        dt = time.perf_counter() - t0
        with self._lock:
            self.stats.compile_seconds[bucket] = dt
        if self.obs.enabled:
            self.obs.event("aot_compile", bucket=bucket, seconds=dt)
        return compiled

    # -- persistent on-disk executable tier -----------------------------

    def _cache_path(self, bucket: int) -> str:
        """One entry per (model, bucket, device slot). The full
        fingerprint lives in the entry header, not the name — so a jax
        upgrade, weight change (hot-swap), or platform move reads as a
        *typed mismatch* that recompiles and overwrites in place,
        instead of silently orphaning stale files."""
        return os.path.join(
            self.cache_dir,
            f"{self.handle.name}-b{bucket}-d{self.device.id}.aotx",
        )

    def _cache_read(self, bucket: int):
        """Deserialize one entry; None on a clean miss (no file), raises
        AotCacheError on a torn / corrupt / mismatched entry."""
        path = self._cache_path(bucket)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return None
        except OSError as e:
            raise AotCacheError(f"unreadable cache entry {path}: {e}")
        if len(blob) < len(_AOT_MAGIC) + 8 or not blob.startswith(_AOT_MAGIC):
            raise AotCacheError(f"bad magic / torn header in {path}")
        off = len(_AOT_MAGIC)
        hlen = int.from_bytes(blob[off:off + 8], "big")
        off += 8
        if len(blob) < off + hlen:
            raise AotCacheError(f"torn header in {path}")
        try:
            header = json.loads(blob[off:off + hlen])
        except ValueError as e:
            raise AotCacheError(f"corrupt header in {path}: {e}")
        fp = dict(self._fingerprint, bucket=bucket)
        if header.get("fingerprint") != fp:
            raise AotCacheError(
                f"fingerprint mismatch in {path} (stale jax/XLA toolchain, "
                f"different device, or different weights)"
            )
        payload = blob[off + hlen:]
        if len(payload) != header.get("nbytes"):
            raise AotCacheError(
                f"torn payload in {path}: {len(payload)} != "
                f"{header.get('nbytes')} bytes"
            )
        if hashlib.sha256(payload).hexdigest() != header.get("sha256"):
            raise AotCacheError(f"payload checksum mismatch in {path}")
        from jax.experimental import serialize_executable as se

        try:
            raw, in_tree, out_tree = pickle.loads(payload)
            return se.deserialize_and_load(raw, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 — any load failure degrades
            raise AotCacheError(f"undeserializable entry {path}: {e}")

    def _cache_load(self, bucket: int):
        """The accounting wrapper around ``_cache_read``: returns the
        executable or None, counting hit / miss / corrupt and emitting
        the matching journal event. Corruption warns AotCacheWarning —
        the caller recompiles."""
        try:
            ex = self._cache_read(bucket)
        except AotCacheError as e:
            warnings.warn(
                f"AOT cache entry unusable, recompiling bucket {bucket}: "
                f"{e}",
                AotCacheWarning,
                stacklevel=3,
            )
            with self._lock:
                self.stats.aot_cache_corrupt += 1
            if self.obs.enabled:
                self.obs.event("aot_cache_corrupt", bucket=bucket,
                               reason=str(e))
            return None
        with self._lock:
            if ex is not None:
                self.stats.aot_cache_hits += 1
            else:
                self.stats.aot_cache_misses += 1
        if self.obs.enabled:
            self.obs.event(
                "aot_cache_hit" if ex is not None else "aot_cache_miss",
                bucket=bucket,
            )
        return ex

    def _cache_store(self, bucket: int, compiled) -> None:
        """Serialize one executable atomically (tmp + rename, same torn-
        write discipline as checkpoint.save). A backend that cannot
        serialize disables the cache for this engine with one warning."""
        from jax.experimental import serialize_executable as se

        try:
            payload = pickle.dumps(se.serialize(compiled))
        except Exception as e:  # noqa: BLE001 — backend-dependent support
            with self._lock:
                self._cache_ok = False
            warnings.warn(
                f"AOT executable serialization unsupported on this "
                f"backend; persistent cache disabled: {e}",
                AotCacheWarning,
                stacklevel=3,
            )
            return
        header = json.dumps({
            "fingerprint": dict(self._fingerprint, bucket=bucket),
            "nbytes": len(payload),
            "sha256": hashlib.sha256(payload).hexdigest(),
        }).encode()
        path = self._cache_path(bucket)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(_AOT_MAGIC)
            f.write(len(header).to_bytes(8, "big"))
            f.write(header)
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _obtain(self, bucket: int):
        """Load-or-compile one bucket (not yet in the memory map).
        Returns (executable, from_disk_cache)."""
        if self._cache_ok:
            ex = self._cache_load(bucket)
            if ex is not None:
                return ex, True
        ex = self._compile(bucket)
        if self._cache_ok:
            self._cache_store(bucket, ex)
        return ex, False

    def _executable(self, bucket: int):
        with self._lock:
            ex = self._exec.get(bucket)
            if ex is not None:
                self.stats.aot_hits += 1
                return ex
        # Compile outside the lock (minutes on big models — don't block
        # other buckets), then publish; a racing double-compile is
        # harmless and keeps the first one.
        ex, from_disk = self._obtain(bucket)
        with self._lock:
            if bucket not in self._exec:
                self._exec[bucket] = ex
                if not from_disk:
                    self.stats.aot_compiles += 1
            else:
                ex = self._exec[bucket]
            return ex

    def precompile(self) -> Dict[int, float]:
        """Compile every bucket now; returns {bucket: compile seconds}.
        Idempotent — already-cached buckets are skipped (not counted as
        hits: only predict-path lookups feed the hit counter). With a
        persistent cache attached, buckets deserialized from disk count
        as cache hits, not compiles — a warm cold start compiles
        nothing (the restart-to-first-response win the supervisor's
        crash-fast restart depends on)."""
        for b in self.buckets:
            with self._lock:
                if b in self._exec:
                    continue
            ex, from_disk = self._obtain(b)
            with self._lock:
                if b not in self._exec:
                    self._exec[b] = ex
                    if not from_disk:
                        self.stats.aot_compiles += 1
        return dict(self.stats.compile_seconds)

    def predict(self, x) -> np.ndarray:
        """(n, *in_shape) float32 → (n, n_outputs) float32.

        Pads to the nearest bucket, runs the bucket's AOT executable on
        this engine's device, and slices the padding back off. The padded
        rows run through the model and are discarded — zeros are safe
        because no eval-mode op in the registered models mixes
        information across the batch dim (BN uses running stats)."""
        import jax

        x = np.asarray(x, dtype=np.float32)
        if x.shape[1:] != tuple(self.handle.in_shape):
            raise ValueError(
                f"expected (n, {', '.join(map(str, self.handle.in_shape))}), "
                f"got {x.shape}"
            )
        n = x.shape[0]
        bucket = self.bucket_for(n)
        if n < bucket:
            pad = np.zeros((bucket - n, *x.shape[1:]), x.dtype)
            x = np.concatenate([x, pad], axis=0)
        ex = self._executable(bucket)
        y = ex(jax.device_put(x, self.device))
        with self._lock:
            self.stats.predicts += 1
        return np.asarray(y)[:n]


class ReplicaPool:
    """n_replicas engine copies pinned round-robin across local devices.

    Weights are restored/initialized ONCE on host and re-pinned per
    replica; each engine owns its per-device AOT executables, so
    independent batches dispatched to different replicas run genuinely
    concurrently (no shared compile cache, no shared device queue).
    Replica selection (`next_replica`) is a deterministic round-robin —
    tests replay it exactly.

    Failure-aware: ``kill``/``evict`` mark a replica dead (its predict
    raises ReplicaDead, round-robin skips it), ``respawn`` re-pins a
    fresh Engine from the host-side weight copies the pool keeps for
    exactly this purpose. The batcher's failover path drives the
    evict → retry-on-survivor → respawn sequence (chaos
    ``kill-replica@SEQ`` is the test harness for it).

    Elastic (serve/autoscaler.py drives these): ``grow`` adds serving
    capacity — it revives a dead slot via the respawn path when one
    exists, else appends a fresh pinned Engine; ``drain`` makes a
    replica unroutable while leaving it alive so in-flight batches
    complete; ``retire`` then frees the drained slot (a later ``grow``
    reuses it). Slot indices are stable for the pool's lifetime.
    """

    def __init__(
        self,
        handle,
        *,
        n_replicas: int = 1,
        checkpoint: Optional[str] = None,
        max_batch: int = 64,
        devices=None,
        seed: int = 0,
        precompile: bool = False,
        obs: Optional["obs_lib.Obs"] = None,
        cache_dir: Optional[str] = None,
        plan_fingerprint: Optional[str] = None,
    ):
        import jax

        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        devices = list(devices) if devices is not None else jax.devices()
        params, model_state = load_or_init(handle, checkpoint, seed)
        # Kept host-side for respawn: a replacement replica re-pins these
        # (the dead replica's device copies are unreachable by definition).
        self._params = params
        self._model_state = model_state
        self.devices = devices
        self._precompile = precompile
        self.obs = obs
        self.cache_dir = cache_dir
        self.plan_fingerprint = plan_fingerprint
        self.engines = [
            Engine(
                handle,
                params=params,
                model_state=model_state,
                max_batch=max_batch,
                device=devices[i % len(devices)],
                precompile=precompile,
                obs=obs,
                cache_dir=cache_dir,
                plan_fingerprint=plan_fingerprint,
            )
            for i in range(n_replicas)
        ]
        self.handle = handle
        self.max_batch = max_batch
        self._rr = 0
        self._alive = [True] * n_replicas
        self._draining = [False] * n_replicas
        self._lock = threading.Lock()

    @property
    def n_replicas(self) -> int:
        return len(self.engines)

    def alive(self) -> List[int]:
        """Indices of live replicas (draining ones included — they are
        still serving their in-flight batches)."""
        with self._lock:
            return [i for i, a in enumerate(self._alive) if a]

    def routable(self) -> List[int]:
        """Indices round-robin will hand out: alive and not draining —
        the pool's effective serving capacity (the autoscaler's sizing
        input)."""
        with self._lock:
            return [
                i for i, a in enumerate(self._alive)
                if a and not self._draining[i]
            ]

    def kill(self, i: int) -> None:
        """Mark replica ``i`` dead: its predict raises ReplicaDead and
        round-robin skips it until ``respawn``. The chaos injection point
        (``kill-replica@SEQ``) — and what ``evict`` aliases after a real
        failure."""
        with self._lock:
            self._alive[i] = False
            self._draining[i] = False

    # Eviction after an observed failure is the same state change as a
    # chaos kill — one implementation, two call sites with different
    # intents (inject vs respond).
    evict = kill

    def respawn(self, i: int, device=None) -> int:
        """Re-pin a replacement for replica ``i`` from the pool's
        host-side weights; returns ``i`` (now live again).

        ``device`` overrides the pin (default: the slot's original
        ``devices[i % len(devices)]`` assignment — on a CPU/chaos run the
        device object is still healthy; a real device loss passes the
        replacement device here). The fresh Engine has an empty AOT
        cache: buckets recompile lazily on first use (or eagerly when the
        pool was built with ``precompile=True``)."""
        with self._lock:
            params, model_state = self._params, self._model_state
        eng = Engine(
            self.handle,
            params=params,
            model_state=model_state,
            max_batch=self.max_batch,
            device=device if device is not None
            else self.devices[i % len(self.devices)],
            precompile=self._precompile,
            obs=self.obs,
            cache_dir=self.cache_dir,
            plan_fingerprint=self.plan_fingerprint,
        )
        with self._lock:
            self.engines[i] = eng
            self._alive[i] = True
            self._draining[i] = False
        return i

    def grow(self, device=None) -> int:
        """Add one serving replica; returns its slot index.

        A dead slot (killed/retired and never respawned) is revived via
        the respawn path — same machinery as failover recovery. With no
        free slot, a fresh Engine is appended, pinned to the next device
        in the round-robin placement (or ``device``). The Engine builds
        OUTSIDE the pool lock (compiles can take a while) and publishes
        atomically; existing slot indices never move."""
        with self._lock:
            free = [i for i, a in enumerate(self._alive) if not a]
        if free:
            return self.respawn(free[0], device=device)
        with self._lock:
            params, model_state = self._params, self._model_state
        eng = Engine(
            self.handle,
            params=params,
            model_state=model_state,
            max_batch=self.max_batch,
            device=device if device is not None
            else self.devices[len(self.engines) % len(self.devices)],
            precompile=self._precompile,
            obs=self.obs,
            cache_dir=self.cache_dir,
            plan_fingerprint=self.plan_fingerprint,
        )
        with self._lock:
            self.engines.append(eng)
            self._alive.append(True)
            self._draining.append(False)
            return len(self.engines) - 1

    def set_weights(self, params: Any, model_state: Any = None) -> None:
        """Swap the pool's host-side weight copies: every replica built
        FROM NOW ON (grow / respawn) serves the new weights; existing
        replicas keep serving the old ones until retired. This is the
        hot-swap primitive (serve/supervisor.py drives the rolling
        grow-new → drain-old → retire sequence around it) — deliberately
        NOT an in-place mutation of live engines, whose executables
        close over the old arrays."""
        with self._lock:
            self._params = params
            self._model_state = model_state if model_state is not None else {}

    def drain(self, i: int) -> None:
        """Make replica ``i`` unroutable while leaving it alive: no new
        batch is pinned to it, but batches already dispatched to it
        still execute. The scale-down half-step — ``retire`` completes
        it once the caller has seen the in-flight count hit zero."""
        with self._lock:
            self._draining[i] = True

    def undrain(self, i: int) -> None:
        """Abort a drain: return a still-alive replica to rotation (the
        hot-swap stuck-drain escape hatch — a swap that can't empty a
        replica's in-flight queue must put it back, not kill it)."""
        with self._lock:
            if self._alive[i]:
                self._draining[i] = False

    def retire(self, i: int) -> None:
        """Free a drained slot: the replica is gone (predict raises
        ReplicaDead) and the slot is available for a future ``grow``."""
        with self._lock:
            self._alive[i] = False
            self._draining[i] = False

    def next_replica(self) -> int:
        """Deterministic round-robin over ROUTABLE replicas (dead and
        draining slots are skipped without consuming a turn for the
        survivors)."""
        with self._lock:
            for _ in range(len(self.engines)):
                i = self._rr
                self._rr = (self._rr + 1) % len(self.engines)
                if self._alive[i] and not self._draining[i]:
                    return i
        raise ReplicaDead(-1, "no live replicas in the pool")

    def precompile(self) -> Dict[int, float]:
        out: Dict[int, float] = {}
        for e in self.engines:
            out.update(e.precompile())
        return out

    def predict(self, x, replica: Optional[int] = None) -> Tuple[np.ndarray, int]:
        """Run one batch on a replica (round-robin unless pinned).
        Returns (outputs, replica index) so callers can audit placement.
        A pinned dead replica raises ReplicaDead — the batcher failover
        trigger."""
        i = self.next_replica() if replica is None else replica
        with self._lock:
            if not self._alive[i]:
                raise ReplicaDead(i)
        return self.engines[i].predict(x), i
