"""Seeded, deterministic traffic scenarios with explicit SLO gates.

loadgen.py answers "what does this stack do under a fixed arrival
pattern"; this module answers the robustness question — "does the stack
hold its SLO through realistic traffic shapes and injected faults".
Each scenario is a seeded arrival schedule driven through a live
batcher, measured client-side, cross-checked server-side against the
request-conservation law, and judged against explicit p99 / shed-rate
gates (the numbers ``--suite serve`` and the dryrun leg enforce):

- **diurnal** — an inhomogeneous Poisson day: the rate sweeps
  trough → peak → trough sinusoidally (piecewise-homogeneous slices,
  seeded gaps). Proves the steady-state ladder: sub-capacity traffic
  must shed nothing at any point of the curve.
- **flash-crowd** — a base rate with a several-× arrival spike in the
  middle. Clients retry sheds with seeded backoff (a blocked client's
  behavior), so the shed gate measures *unrecovered* demand — the
  scenario the autoscaler's scale-up must drive back to 0.
- **slow-client** — closed-loop clients with think time between
  requests: offered load self-regulates (classic backpressure), the
  queue stays shallow, and the gates pin that nothing is shed and p99
  stays near service time.
- **chaos-kill** — steady traffic with ``kill-replica@SEQ`` armed: a
  replica dies mid-traffic and the failover path (evict → retry on
  survivor → respawn) must keep conservation AND the gates.
- **chaos-slow** — steady traffic with ``slow-replica@SEQ:MS`` armed:
  a straggler stalls one batch. With a stall chosen past the p99 gate
  this scenario MUST trip it — the anti-vacuity probe proving the gate
  can fail (benches/run.py asserts the trip).

Determinism: payloads, arrival gaps, priorities, and retry backoff all
derive from ``seed``. Wall-clock scheduling jitter moves individual
latencies, so gates carry CPU-scale headroom, but the request sequence
itself replays exactly.

The **net suites** (``run_net`` + NET_SCENARIOS) repeat the exercise
one boundary further out — over the real socket of serve/net.py, with
conservation judged at the wire tier (WireStats delta) as well:

- **net-steady** — closed-loop socket clients, no faults: the wire
  baseline every other net gate is measured against.
- **net-slow-loris** — one client stalls mid-request past the read
  deadline (``slow-loris@SEQ:MS`` armed client-side). The server must
  reap it as *expired* — never a hung handler thread — and the run
  asserts ``reaped >= 1`` on top of conservation.
- **net-kill-endpoint** — ``kill-endpoint@SEQ`` armed server-side:
  the endpoint dies mid-traffic, in-flight wire requests are journaled
  ``net_failed``, and the supervisor's bounded-backoff respawn (same
  port) lets client retries carry every logical request through —
  run WITHOUT a supervisor and the gate trips, which is the
  anti-vacuity control arm the dryrun leg proves.
- **net-hot-swap-diurnal** — the diurnal shape driven over the wire
  with a weight hot-swap triggered mid-peak: the grow → drain →
  retire roll must finish with ``failed_delta == 0`` and conservation
  intact at both tiers (the zero-downtime gate).
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from parallel_cnn_tpu.serve.batcher import (
    DeadlineExceeded,
    DynamicBatcher,
    Overloaded,
)
from parallel_cnn_tpu.serve.loadgen import make_samples
from parallel_cnn_tpu.utils.metrics import Histogram

#: Conservation-law keys (server-side stats delta must balance).
_COUNTER_KEYS = ("submitted", "completed", "shed", "expired", "failed")


@dataclasses.dataclass
class ScenarioReport:
    """One scenario run: client-side outcomes, server-side conservation,
    and the gate verdicts."""

    name: str
    seed: int
    requests: int          # logical requests (retries collapse into one)
    completed: int
    shed: int              # logical requests never accepted
    expired: int
    errors: int
    seconds: float
    latency: Histogram     # submit→result per completed request, seconds
    p99_gate_ms: float
    shed_gate: float
    server: Dict[str, int]          # stats delta over the run
    conservation_ok: bool

    @property
    def p99_ms(self) -> Optional[float]:
        p = self.latency.percentile(99)
        return p * 1e3 if p is not None else None

    @property
    def shed_rate(self) -> float:
        return self.shed / self.requests if self.requests else 0.0

    def gates(self) -> Dict[str, bool]:
        """Per-gate verdicts; the conservation law is always a gate."""
        p99 = self.p99_ms
        return {
            "p99": p99 is not None and p99 <= self.p99_gate_ms,
            "shed_rate": self.shed_rate <= self.shed_gate,
            "conservation": self.conservation_ok and self.errors == 0,
        }

    @property
    def passed(self) -> bool:
        return all(self.gates().values())

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "seed": self.seed,
            "requests": self.requests,
            "completed": self.completed,
            "shed": self.shed,
            "expired": self.expired,
            "errors": self.errors,
            "seconds": round(self.seconds, 4),
            "p99_ms": self.p99_ms,
            "shed_rate": round(self.shed_rate, 4),
            "gates": self.gates(),
            "passed": self.passed,
            "server": self.server,
        }


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """A named scenario: traffic builder + default gates."""

    name: str
    p99_ms: float            # default p99 gate (CPU-scale headroom)
    max_shed_rate: float     # default shed-rate gate
    retry: bool              # clients retry Overloaded sheds
    needs_chaos: Optional[str]   # required armed fault, or None
    phases: Tuple[Tuple[float, float], ...] = ()   # (seconds, req/s)
    closed: bool = False     # closed-loop (slow-client) instead of open
    n_requests: int = 0      # closed-loop volume
    concurrency: int = 0     # closed-loop client count
    think_ms: float = 0.0    # closed-loop think time per client


SCENARIOS: Dict[str, ScenarioSpec] = {
    # Sub-capacity sinusoid: 2 cycles, trough 100 → peak 500 req/s.
    "diurnal": ScenarioSpec(
        name="diurnal", p99_ms=250.0, max_shed_rate=0.0, retry=False,
        needs_chaos=None,
        phases=tuple(
            (0.08, 100.0 + 400.0 * 0.5 * (1.0 - math.cos(
                2.0 * math.pi * 2.0 * (i + 0.5) / 10.0)))
            for i in range(10)
        ),
    ),
    # 6× arrival spike mid-run; retries make shed-rate measure
    # *unrecovered* demand (what scale-up must drive to 0).
    "flash-crowd": ScenarioSpec(
        name="flash-crowd", p99_ms=500.0, max_shed_rate=0.0, retry=True,
        needs_chaos=None,
        phases=((0.2, 250.0), (0.25, 1500.0), (0.25, 250.0)),
    ),
    # Closed loop with think time: backpressure keeps the queue shallow.
    "slow-client": ScenarioSpec(
        name="slow-client", p99_ms=250.0, max_shed_rate=0.0, retry=False,
        needs_chaos=None, closed=True,
        n_requests=64, concurrency=4, think_ms=4.0,
    ),
    # Steady traffic through a mid-run replica death (failover path).
    "chaos-kill": ScenarioSpec(
        name="chaos-kill", p99_ms=500.0, max_shed_rate=0.0, retry=True,
        needs_chaos="kill-replica",
        phases=((0.5, 400.0),),
    ),
    # Steady traffic through a mid-run straggler stall; with a stall
    # beyond the p99 gate, this scenario MUST report passed=False.
    "chaos-slow": ScenarioSpec(
        name="chaos-slow", p99_ms=150.0, max_shed_rate=0.0, retry=True,
        needs_chaos="slow-replica",
        phases=((0.5, 400.0),),
    ),
}


def _phase_offsets(phases, rng) -> List[float]:
    """Absolute arrival offsets (seconds) for piecewise-homogeneous
    Poisson phases — seeded, so the schedule replays exactly."""
    out: List[float] = []
    t0 = 0.0
    for dur, rate in phases:
        if rate <= 0:
            raise ValueError(f"phase rate must be > 0, got {rate}")
        t = t0
        while True:
            t += float(rng.exponential(1.0 / rate))
            if t > t0 + dur:
                break
            out.append(t)
        t0 += dur
    return out


def _settled_delta(stats, before: Dict[str, int],
                   timeout_s: float = 5.0) -> Tuple[Dict[str, int], bool]:
    """Server-side counter delta once it balances. The last future can
    resolve a beat before its on_complete lands, so poll briefly for
    submitted == completed + shed + expired + failed before judging."""
    deadline = time.monotonic() + timeout_s
    while True:
        snap = stats.snapshot()
        delta = {k: snap[k] - before.get(k, 0) for k in _COUNTER_KEYS}
        balanced = delta["submitted"] == (
            delta["completed"] + delta["shed"] + delta["expired"]
            + delta["failed"]
        )
        if balanced or time.monotonic() > deadline:
            return delta, balanced
        time.sleep(0.002)


def _priority_for(rng, best_effort_frac: float) -> str:
    if best_effort_frac > 0 and rng.random() < best_effort_frac:
        return "best-effort"
    return "guaranteed"


def _drive_open(
    batcher: DynamicBatcher,
    spec: ScenarioSpec,
    *,
    seed: int,
    deadline_ms: Optional[float],
    best_effort_frac: float,
    retry_attempts: int,
) -> Dict[str, Any]:
    """Paced submission along the seeded schedule; a shed request is
    retried in place (with seeded backoff) when the spec says clients
    retry — later arrivals shift behind the retries, exactly as a
    blocked client shifts real traffic."""
    rng = np.random.default_rng(seed)
    offsets = _phase_offsets(spec.phases, rng)
    samples = make_samples(
        min(len(offsets), 64) or 1, batcher.pool.handle.in_shape, seed=seed
    )
    counters = {"completed": 0, "shed": 0, "expired": 0, "errors": 0}
    lock = threading.Lock()
    latency = Histogram()
    futures: List[Tuple[float, Any]] = []
    attempts = retry_attempts if spec.retry else 1
    backoffs = rng.uniform(0.001, 0.004, size=max(len(offsets), 1))

    def waiter(items):
        for t_sub, fut in items:
            try:
                fut.result(timeout=60.0)
                with lock:
                    counters["completed"] += 1
                # fut.t_done, not now(): the waiter drains after the
                # whole schedule has been paced out, so observe time
                # would charge early requests the full run duration.
                latency.record((fut.t_done or time.monotonic()) - t_sub)
            except DeadlineExceeded:
                with lock:
                    counters["expired"] += 1
            except BaseException:  # noqa: BLE001 — scenario must finish
                with lock:
                    counters["errors"] += 1

    t_start = time.monotonic()
    for i, off in enumerate(offsets):
        delay = t_start + off - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        x = samples[i % len(samples)]
        prio = _priority_for(rng, best_effort_frac)
        fut = None
        for attempt in range(attempts):
            try:
                fut = batcher.submit(x, deadline_ms=deadline_ms,
                                     priority=prio)
                break
            except Overloaded:
                if attempt < attempts - 1:
                    time.sleep(float(backoffs[i % len(backoffs)])
                               * (attempt + 1))
        if fut is None:
            counters["shed"] += 1
        else:
            futures.append((time.monotonic(), fut))
    waiter(futures)
    return {
        "requests": len(offsets),
        "seconds": time.monotonic() - t_start,
        "latency": latency,
        **counters,
    }


def _drive_closed(
    batcher: DynamicBatcher,
    spec: ScenarioSpec,
    *,
    seed: int,
    deadline_ms: Optional[float],
    best_effort_frac: float,
) -> Dict[str, Any]:
    """Closed-loop clients with think time — the slow-client shape."""
    rng = np.random.default_rng(seed)
    samples = make_samples(
        min(spec.n_requests, 64), batcher.pool.handle.in_shape, seed=seed
    )
    prios = [
        _priority_for(rng, best_effort_frac) for _ in range(spec.n_requests)
    ]
    counters = {"completed": 0, "shed": 0, "expired": 0, "errors": 0}
    lock = threading.Lock()
    latency = Histogram()
    next_idx = [0]

    def client() -> None:
        while True:
            with lock:
                i = next_idx[0]
                if i >= spec.n_requests:
                    return
                next_idx[0] += 1
            t_sub = time.monotonic()
            try:
                fut = batcher.submit(
                    samples[i % len(samples)], deadline_ms=deadline_ms,
                    priority=prios[i],
                )
            except Overloaded:
                with lock:
                    counters["shed"] += 1
                continue
            try:
                fut.result(timeout=60.0)
                with lock:
                    counters["completed"] += 1
                latency.record((fut.t_done or time.monotonic()) - t_sub)
            except DeadlineExceeded:
                with lock:
                    counters["expired"] += 1
            except BaseException:  # noqa: BLE001
                with lock:
                    counters["errors"] += 1
            # The slow client: think before the next request — the
            # backpressure that keeps offered load self-regulated.
            time.sleep(spec.think_ms / 1e3)

    threads = [
        threading.Thread(target=client, daemon=True)
        for _ in range(spec.concurrency)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return {
        "requests": spec.n_requests,
        "seconds": time.monotonic() - t0,
        "latency": latency,
        **counters,
    }


def run(
    name: str,
    batcher: DynamicBatcher,
    *,
    seed: int = 0,
    deadline_ms: Optional[float] = None,
    best_effort_frac: float = 0.0,
    retry_attempts: int = 6,
    p99_ms: Optional[float] = None,
    max_shed_rate: Optional[float] = None,
) -> ScenarioReport:
    """Run one named scenario against a live batcher and judge it.

    Gate overrides (``p99_ms`` / ``max_shed_rate``) replace the spec
    defaults; chaos scenarios refuse to run without the matching fault
    armed on the batcher — a chaos gate that never injects would be
    vacuously green."""
    spec = SCENARIOS.get(name)
    if spec is None:
        raise ValueError(
            f"unknown scenario {name!r} (have: {', '.join(SCENARIOS)})"
        )
    if spec.needs_chaos is not None:
        chaos = batcher.chaos
        armed = chaos is not None and (
            (spec.needs_chaos == "kill-replica"
             and chaos.kill_replica_seq is not None)
            or (spec.needs_chaos == "slow-replica"
                and chaos.slow_replica is not None)
        )
        if not armed:
            raise ValueError(
                f"scenario {name!r} needs a ChaosMonkey with "
                f"{spec.needs_chaos}@… armed on the batcher"
            )
    before = {
        k: batcher.stats.snapshot()[k] for k in _COUNTER_KEYS
    }
    if spec.closed:
        out = _drive_closed(
            batcher, spec, seed=seed, deadline_ms=deadline_ms,
            best_effort_frac=best_effort_frac,
        )
    else:
        out = _drive_open(
            batcher, spec, seed=seed, deadline_ms=deadline_ms,
            best_effort_frac=best_effort_frac,
            retry_attempts=retry_attempts,
        )
    server, balanced = _settled_delta(batcher.stats, before)
    return ScenarioReport(
        name=name,
        seed=seed,
        requests=out["requests"],
        completed=out["completed"],
        shed=out["shed"],
        expired=out["expired"],
        errors=out["errors"],
        seconds=out["seconds"],
        latency=out["latency"],
        p99_gate_ms=p99_ms if p99_ms is not None else spec.p99_ms,
        shed_gate=(max_shed_rate if max_shed_rate is not None
                   else spec.max_shed_rate),
        server=server,
        conservation_ok=balanced,
    )


# ---------------------------------------------------------------------------
# Net suites: the same judgment over the real socket (serve/net.py).
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class NetScenarioReport(ScenarioReport):
    """A ScenarioReport with the wire tier judged too: the WireStats
    delta must balance on its own, a slow-loris run must actually reap,
    and a hot-swap run must finish with zero failed and nothing stuck."""

    wire: Dict[str, int] = dataclasses.field(default_factory=dict)
    wire_ok: bool = True
    min_reaped: int = 0
    swap: Optional[Dict[str, Any]] = None

    def gates(self) -> Dict[str, bool]:
        g = super().gates()
        g["wire_conservation"] = self.wire_ok
        if self.min_reaped:
            g["reaped"] = self.wire.get("reaped", 0) >= self.min_reaped
        if self.swap is not None:
            g["hot_swap_zero_failed"] = (
                self.swap.get("failed_delta", 1) == 0
                and not self.swap.get("stuck")
                and len(self.swap.get("swapped", [])) > 0
            )
        return g

    def to_dict(self) -> Dict[str, Any]:
        d = super().to_dict()
        d["wire"] = self.wire
        d["swap"] = self.swap
        return d


@dataclasses.dataclass(frozen=True)
class NetScenarioSpec:
    """A named net scenario: closed-loop socket clients, optionally
    paced along seeded phase offsets, with the gate defaults."""

    name: str
    p99_ms: float
    max_shed_rate: float
    needs_chaos: Optional[str]    # "slow-loris" (client) / "kill-endpoint"
    n_requests: int
    concurrency: int
    phases: Tuple[Tuple[float, float], ...] = ()  # paced arrivals when set
    min_reaped: int = 0           # required reap count (anti-vacuity)
    swap_at_frac: Optional[float] = None  # hot-swap trigger point
    deadline_ms: Optional[float] = None   # per-request budget on the wire


NET_SCENARIOS: Dict[str, NetScenarioSpec] = {
    # The wire baseline: no faults, nothing shed, nothing lost.
    "net-steady": NetScenarioSpec(
        name="net-steady", p99_ms=500.0, max_shed_rate=0.0,
        needs_chaos=None, n_requests=64, concurrency=4,
    ),
    # One client stalls mid-request past the read deadline; the server
    # must reap it as expired (never a hung handler) and keep serving.
    "net-slow-loris": NetScenarioSpec(
        name="net-slow-loris", p99_ms=500.0, max_shed_rate=0.0,
        needs_chaos="slow-loris", n_requests=48, concurrency=4,
        min_reaped=1,
    ),
    # Endpoint dies mid-traffic; with a supervisor the respawn plus
    # client transport-retries carry every logical request through.
    "net-kill-endpoint": NetScenarioSpec(
        name="net-kill-endpoint", p99_ms=1000.0, max_shed_rate=0.0,
        needs_chaos="kill-endpoint", n_requests=64, concurrency=4,
    ),
    # Diurnal pacing with a weight hot-swap triggered mid-peak: the
    # grow → drain → retire roll must lose nothing (zero failed).
    "net-hot-swap-diurnal": NetScenarioSpec(
        name="net-hot-swap-diurnal", p99_ms=1000.0, max_shed_rate=0.0,
        needs_chaos=None, n_requests=0, concurrency=6,
        phases=((0.05, 150.0), (0.1, 400.0), (0.05, 150.0)),
        swap_at_frac=0.4,
    ),
}


def _settled_wire_delta(wire, before: Dict[str, int],
                        timeout_s: float = 5.0) -> Tuple[Dict[str, int], bool]:
    """Wire-tier twin of ``_settled_delta``: poll until the WireStats
    delta balances (a handler may account its terminal outcome a beat
    after the client read the reply)."""
    keys = _COUNTER_KEYS + ("reaped", "conn_opened", "endpoint_deaths")
    deadline = time.monotonic() + timeout_s
    while True:
        snap = wire.snapshot()
        delta = {k: snap[k] - before.get(k, 0) for k in keys}
        balanced = delta["submitted"] == (
            delta["completed"] + delta["shed"] + delta["expired"]
            + delta["failed"]
        )
        if balanced or time.monotonic() > deadline:
            return delta, balanced
        time.sleep(0.002)


def run_net(
    name: str,
    batcher: DynamicBatcher,
    *,
    wire,
    address: Optional[Tuple[str, int]] = None,
    server=None,
    supervisor=None,
    chaos=None,
    swap_params: Any = None,
    swap_state: Any = None,
    obs=None,
    seed: int = 0,
    timeout_s: float = 10.0,
    retry=None,
    p99_ms: Optional[float] = None,
    max_shed_rate: Optional[float] = None,
) -> NetScenarioReport:
    """Run one named net scenario over a live socket endpoint.

    ``wire`` is the (respawn-shared) WireStats of the endpoint;
    ``supervisor`` / ``server`` locate the listener (``address``
    overrides — e.g. a fixed port the supervisor respawns on).
    ``chaos`` is the *client-side* monkey (slow-loris); the
    kill-endpoint arming check reads the *server's* monkey. A hot-swap
    scenario needs ``swap_params`` — the new weights rolled in
    mid-peak via serve.supervisor.hot_swap."""
    from parallel_cnn_tpu.serve import loadgen
    from parallel_cnn_tpu.serve import supervisor as supervisor_lib

    spec = NET_SCENARIOS.get(name)
    if spec is None:
        raise ValueError(
            f"unknown net scenario {name!r} "
            f"(have: {', '.join(NET_SCENARIOS)})"
        )
    endpoint = supervisor.server if supervisor is not None else server
    if address is None:
        if endpoint is None:
            raise ValueError("run_net needs address=, server=, or "
                             "supervisor= to locate the endpoint")
        address = endpoint.address
    # Anti-vacuity: a chaos scenario without its fault armed would be
    # vacuously green — refuse instead (same contract as run()).
    if spec.needs_chaos == "slow-loris":
        if chaos is None or chaos.slow_loris is None:
            raise ValueError(
                f"scenario {name!r} needs a client-side ChaosMonkey with "
                f"slow-loris@SEQ:MS armed"
            )
    elif spec.needs_chaos == "kill-endpoint":
        srv_chaos = endpoint.chaos if endpoint is not None else None
        if srv_chaos is None or srv_chaos.kill_endpoint_seq is None:
            raise ValueError(
                f"scenario {name!r} needs kill-endpoint@SEQ armed on the "
                f"endpoint's ChaosMonkey"
            )
    if spec.swap_at_frac is not None and swap_params is None:
        raise ValueError(f"scenario {name!r} needs swap_params= (the new "
                         f"weights to hot-swap in)")
    rng = np.random.default_rng(seed)
    offsets = _phase_offsets(spec.phases, rng) if spec.phases else []
    n_requests = len(offsets) if offsets else spec.n_requests
    samples = make_samples(
        min(n_requests, 64) or 1, batcher.pool.handle.in_shape, seed=seed
    )
    swap_holder: Dict[str, Any] = {}
    swap_threads: List[threading.Thread] = []
    triggered = [False]
    trigger_lock = threading.Lock()
    swap_idx = (
        int(spec.swap_at_frac * n_requests)
        if spec.swap_at_frac is not None else None
    )
    t_start = time.monotonic()

    def on_request(i: int) -> None:
        if offsets:
            delay = t_start + offsets[min(i, len(offsets) - 1)] \
                - time.monotonic()
            if delay > 0:
                time.sleep(delay)
        if swap_idx is not None and i >= swap_idx:
            with trigger_lock:
                if triggered[0]:
                    return
                triggered[0] = True
            t = threading.Thread(
                target=lambda: swap_holder.update(
                    report=supervisor_lib.hot_swap(
                        batcher.pool, batcher, swap_params, swap_state,
                        obs=obs,
                    )
                ),
                daemon=True, name="hot-swap",
            )
            t.start()
            swap_threads.append(t)

    before_batcher = {
        k: batcher.stats.snapshot()[k] for k in _COUNTER_KEYS
    }
    before_wire = wire.snapshot()
    out = loadgen.run_closed_loop_net(
        address, samples, n_requests=n_requests,
        concurrency=spec.concurrency, deadline_ms=spec.deadline_ms,
        retry=retry, timeout_s=timeout_s, seed=seed, chaos=chaos,
        on_request=on_request if (offsets or swap_idx is not None)
        else None,
    )
    for t in swap_threads:
        t.join(timeout=30.0)
    swap_report = swap_holder.get("report")
    if spec.swap_at_frac is not None and swap_report is None:
        # The trigger never fired (or the swap never finished): that is
        # a failed swap gate, not an absent one.
        swap_report = {"failed_delta": -1, "stuck": [], "swapped": []}
    wire_delta, wire_ok = _settled_wire_delta(wire, before_wire)
    server_delta, balanced = _settled_delta(batcher.stats, before_batcher)
    return NetScenarioReport(
        name=name,
        seed=seed,
        requests=out.requests,
        completed=out.completed,
        shed=out.shed,
        expired=out.expired,
        errors=out.errors,
        seconds=out.seconds,
        latency=out.latency,
        p99_gate_ms=p99_ms if p99_ms is not None else spec.p99_ms,
        shed_gate=(max_shed_rate if max_shed_rate is not None
                   else spec.max_shed_rate),
        server=server_delta,
        conservation_ok=balanced,
        wire=wire_delta,
        wire_ok=wire_ok,
        min_reaped=spec.min_reaped,
        swap=swap_report,
    )
