"""Network front door: a stdlib-only TCP endpoint over the batcher.

The serve stack below this module (engine → batcher → admission →
autoscaler) is in-process; this is the tier that puts a real socket —
and therefore real failure modes — in front of it, without leaving the
standard library (``socketserver`` + ``json``):

- **Protocol**: newline-delimited JSON over a persistent TCP
  connection. Request: ``{"id": N, "x": [...], "deadline_ms": MS?,
  "priority": "guaranteed"|"best-effort"?}``; response:
  ``{"id": N, "ok": true, "y": [...]}`` or ``{"id": N, "ok": false,
  "error": "Overloaded"|"DeadlineExceeded"|"Failed"|"BadRequest",
  "message": ...}``. One handler thread per connection; requests on a
  connection are served in order, concurrency comes from connections
  (exactly how the threaded loadgen clients drive it).
- **Deadline mapping**: a request that carries ``deadline_ms`` is
  latency-bound — it enters ``submit()`` with that budget in the
  ``guaranteed`` class. A request without one inherits the
  per-connection deadline as its budget and rides ``best-effort`` (the
  class the degradation ladder drops first). An explicit ``priority``
  field overrides the inference.
- **Read/write deadlines**: a connection gets ``conn_deadline_ms`` to
  finish delivering each request line; a socket that stalls mid-body
  past it is *reaped* — counted ``expired`` at the wire tier (journal
  ``conn_expired``), connection closed, handler thread freed. The
  slow-loris defense: a dripping client costs one bounded thread for
  one bounded deadline, never a hang. Blocked response writes are
  abandoned the same way. An *idle* connection (no partial request
  buffered) times out and closes quietly — keep-alive gaps between
  requests are not an attack.
- **Conservation over the wire**: every request observed on the socket
  resolves exactly once in :class:`~parallel_cnn_tpu.serve.telemetry.
  WireStats` — ``submitted == completed + shed + expired + failed`` —
  with the wire lifecycle journaled as ``net_submit`` /
  ``net_complete`` / ``net_shed`` / ``net_expired`` / ``net_failed``
  (``obs.conservation(counts, prefix="net_")`` checks the law over the
  journal). The batcher's own law keeps holding one tier down.
- **Chaos**: ``kill-endpoint@SEQ`` (resilience/chaos.py) kills the
  endpoint the moment it has accepted wire request SEQ: in-flight wire
  requests are journaled ``net_failed`` — never silently lost — and
  every connection drops. The supervisor (serve/supervisor.py) is the
  recovery path; without it the gate trips, which is the point.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from parallel_cnn_tpu import obs as obs_lib
from parallel_cnn_tpu.serve.batcher import DeadlineExceeded, Overloaded
from parallel_cnn_tpu.serve.telemetry import WireStats

#: Cap on one request line; a line that exceeds it is a BadRequest, not
#: an unbounded buffer (the memory twin of the read deadline).
MAX_LINE_BYTES = 8 * 1024 * 1024


def encode_request(rid: int, x, deadline_ms: Optional[float] = None,
                   priority: Optional[str] = None) -> bytes:
    """The client-side wire encoding (loadgen's socket transport and the
    tests share it, so the protocol lives in exactly one place)."""
    req: Dict[str, Any] = {"id": rid, "x": np.asarray(x).tolist()}
    if deadline_ms is not None:
        req["deadline_ms"] = deadline_ms
    if priority is not None:
        req["priority"] = priority
    return json.dumps(req).encode() + b"\n"


class _TcpServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    # Respawn-on-the-same-port is the supervisor contract; without
    # SO_REUSEADDR the TIME_WAIT from the killed endpoint would block
    # the rebind for minutes.
    allow_reuse_address = True


class NetServer:
    """The endpoint: a threaded TCP listener resolving wire requests
    through a DynamicBatcher.

    ``wire`` (a WireStats) is shared across supervisor respawns so the
    conservation law spans restarts; ``chaos`` arms ``kill-endpoint@``.
    ``port=0`` binds an ephemeral port, reported on ``self.port``.
    """

    def __init__(
        self,
        batcher,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        conn_deadline_ms: float = 2000.0,
        wire: Optional[WireStats] = None,
        chaos=None,
        obs: Optional["obs_lib.Obs"] = None,
        seq_start: int = 0,
    ):
        if conn_deadline_ms <= 0:
            raise ValueError(
                f"conn_deadline_ms must be > 0, got {conn_deadline_ms}"
            )
        self.batcher = batcher
        self.wire = wire if wire is not None else WireStats()
        self.chaos = chaos
        self.obs = obs if obs is not None else obs_lib.NOOP
        self.conn_deadline_s = conn_deadline_ms / 1e3
        self._lock = threading.Lock()
        # Wire-request sequence — the chaos schedule's clock. Starts at
        # ``seq_start`` so a respawned endpoint continues the killed
        # one's numbering instead of replaying its chaos window.
        self._seq = seq_start
        # seq -> claimed flag for wire requests submitted to the batcher
        # whose reply has not been written. kill() claims them (journals
        # net_failed); a handler whose entry was claimed stays silent —
        # exactly one terminal outcome per wire request.
        self._inflight: Dict[int, bool] = {}
        self._conns: set = set()
        self._killed = False
        self._closed = False
        server = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):  # noqa: D102 — protocol loop below
                server._handle_conn(self.request)

        self._tcp = _TcpServer((host, port), _Handler)
        self.host, self.port = self._tcp.server_address[:2]
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, kwargs={"poll_interval": 0.01},
            name=f"serve-net-{self.port}", daemon=True,
        )

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "NetServer":
        self._thread.start()
        return self

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    @property
    def alive(self) -> bool:
        with self._lock:
            return not (self._killed or self._closed)

    @property
    def killed(self) -> bool:
        with self._lock:
            return self._killed

    def next_seq(self) -> int:
        """Current wire-sequence watermark (a respawn's ``seq_start``)."""
        with self._lock:
            return self._seq

    def kill(self, reason: str = "chaos") -> None:
        """Abrupt endpoint death (the ``kill-endpoint@`` injection
        point): journal every in-flight wire request as ``net_failed``
        — the reconciliation that makes them lost loudly, not silently
        — then drop the listener and every connection."""
        with self._lock:
            if self._killed or self._closed:
                return
            self._killed = True
            inflight = [s for s, claimed in self._inflight.items()
                        if not claimed]
            for s in inflight:
                self._inflight[s] = True
            conns = list(self._conns)
        self.wire.on_failed(len(inflight))
        self.wire.on_endpoint_death()
        if self.obs.enabled:
            for s in inflight:
                self.obs.event("net_failed", seq=s, reason="endpoint died")
            self.obs.event(
                "endpoint_killed", port=self.port, reason=reason,
                inflight_failed=len(inflight),
            )
        self._teardown(conns)

    def close(self) -> None:
        """Graceful stop (test teardown / process exit): no in-flight
        reconciliation drama, just stop serving."""
        with self._lock:
            if self._killed or self._closed:
                return
            self._closed = True
            conns = list(self._conns)
        self._teardown(conns)

    def _teardown(self, conns) -> None:
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self._tcp.shutdown()
        self._tcp.server_close()
        if self._thread.is_alive():
            self._thread.join(timeout=5)

    def __enter__(self) -> "NetServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- wire accounting helpers ----------------------------------------

    def _serving(self) -> bool:
        with self._lock:
            return not (self._killed or self._closed)

    def _next_seq(self) -> int:
        with self._lock:
            s = self._seq
            self._seq += 1
            return s

    def _track(self, seq: int) -> None:
        with self._lock:
            self._inflight[seq] = False

    def _untrack(self, seq: int) -> bool:
        """Remove a wire request from the in-flight set; True when
        kill() already claimed (and accounted) it."""
        with self._lock:
            return self._inflight.pop(seq, False)

    # -- the per-connection protocol loop -------------------------------

    def _handle_conn(self, sock) -> None:
        with self._lock:
            if self._killed or self._closed:
                return
            self._conns.add(sock)
        self.wire.on_conn_open()
        if self.obs.enabled:
            self.obs.event("conn_open", port=self.port)
        try:
            self._conn_loop(sock)
        finally:
            with self._lock:
                self._conns.discard(sock)
            self.wire.on_conn_close()
            try:
                sock.close()
            except OSError:
                pass

    def _read_line(self, sock, buf: bytearray) -> Optional[bytes]:
        """One request line within the read deadline. The budget runs
        from the first byte of THIS request — a drip-feeding client
        cannot reset it per byte. Returns None to close the connection
        (idle timeout, EOF, reap, or shutdown); a reaped partial has
        already been accounted."""
        line_deadline = (
            time.monotonic() + self.conn_deadline_s if buf else None
        )
        while True:
            nl = buf.find(b"\n")
            if nl >= 0:
                line = bytes(buf[:nl])
                del buf[:nl + 1]
                return line
            if len(buf) > MAX_LINE_BYTES:
                self._reap(sock, len(buf), "request line too long")
                return None
            now = time.monotonic()
            if line_deadline is None:
                timeout = self.conn_deadline_s
            else:
                timeout = line_deadline - now
                if timeout <= 0:
                    self._reap(sock, len(buf), "read deadline")
                    return None
            try:
                sock.settimeout(timeout)
                chunk = sock.recv(65536)
            except socket.timeout:
                if buf:
                    self._reap(sock, len(buf), "read deadline")
                return None
            except OSError:
                if buf and self._serving():
                    self._reap(sock, len(buf), "connection lost mid-body")
                return None
            if not chunk:
                if buf and self._serving():
                    self._reap(sock, len(buf), "EOF mid-body")
                return None
            if not buf:
                line_deadline = time.monotonic() + self.conn_deadline_s
            buf.extend(chunk)

    def _reap(self, sock, n_bytes: int, why: str) -> None:
        """A request that never finished arriving is still a wire
        request: submitted and expired in the same breath, so the
        conservation law sees it instead of a silent drop."""
        seq = self._next_seq()
        self.wire.on_submit()
        self.wire.on_expired(1, reaped=True)
        if self.obs.enabled:
            self.obs.event("net_submit", seq=seq, partial=True)
            self.obs.event("net_expired", seq=seq, reaped=True)
            self.obs.event(
                "conn_expired", seq=seq, buffered=n_bytes, reason=why,
            )

    def _conn_loop(self, sock) -> None:
        buf = bytearray()
        while self._serving():
            line = self._read_line(sock, buf)
            if line is None:
                return
            if not line.strip():
                continue
            if not self._one_request(sock, line):
                return

    def _one_request(self, sock, line: bytes) -> bool:
        """Resolve one complete wire request; False closes the conn."""
        seq = self._next_seq()
        self.wire.on_submit()
        if self.obs.enabled:
            self.obs.event("net_submit", seq=seq)
        if self.chaos is not None and self.chaos.kill_endpoint_at(seq):
            # Chaos: the endpoint dies having accepted this request —
            # kill() below claims it (and every other in-flight one) as
            # net_failed; the client sees a dropped connection.
            self._track(seq)
            self.kill(reason=f"chaos kill-endpoint@{seq}")
            return False
        try:
            req = json.loads(line)
            rid = req["id"]
            x = np.asarray(req["x"], dtype=np.float32)
            deadline_ms = req.get("deadline_ms")
            # The deadline → admission-class mapping (module docstring):
            # an explicit budget marks the request latency-bound.
            priority = req.get("priority") or (
                "guaranteed" if deadline_ms is not None else "best-effort"
            )
            budget = (
                float(deadline_ms) if deadline_ms is not None
                else self.conn_deadline_s * 1e3
            )
        except (ValueError, KeyError, TypeError) as e:
            self.wire.on_failed()
            if self.obs.enabled:
                self.obs.event("net_failed", seq=seq, reason="bad request")
            return self._write(sock, {
                "id": None, "ok": False, "error": "BadRequest",
                "message": str(e),
            })
        try:
            fut = self.batcher.submit(x, deadline_ms=budget,
                                      priority=priority)
        except Overloaded as e:
            self.wire.on_shed()
            if self.obs.enabled:
                self.obs.event("net_shed", seq=seq)
            return self._write(sock, {
                "id": rid, "ok": False, "error": "Overloaded",
                "message": str(e),
            })
        except (ValueError, RuntimeError) as e:
            self.wire.on_failed()
            if self.obs.enabled:
                self.obs.event("net_failed", seq=seq, reason=str(e))
            return self._write(sock, {
                "id": rid, "ok": False, "error": "BadRequest",
                "message": str(e),
            })
        self._track(seq)
        outcome, payload = self._await(fut, rid, budget)
        if self._untrack(seq):
            # kill() already journaled this one as net_failed; the
            # connection is gone — stay silent, account nothing twice.
            return False
        wrote = self._write(sock, payload)
        if not wrote and outcome == "complete":
            # The answer existed but the write deadline blew: at the
            # wire tier the client never got it — expired, not served.
            outcome = "expired"
            payload = None
        if outcome == "complete":
            self.wire.on_complete()
        elif outcome == "expired":
            self.wire.on_expired()
        else:
            self.wire.on_failed()
        if self.obs.enabled:
            self.obs.event(f"net_{outcome}", seq=seq)
        return wrote

    def _await(self, fut, rid, budget_ms: float):
        """Wait out one batcher future, polling so an endpoint kill
        unblocks the handler promptly. The wait is bounded: the request
        budget plus headroom for dispatch — a wedged future resolves as
        Failed rather than pinning the thread."""
        deadline = time.monotonic() + budget_ms / 1e3 + 30.0
        while True:
            try:
                y = fut.result(timeout=0.05)
                return "complete", {"id": rid, "ok": True, "y": y.tolist()}
            except TimeoutError:
                if not self._serving() or time.monotonic() > deadline:
                    return "failed", {
                        "id": rid, "ok": False, "error": "Failed",
                        "message": "endpoint shutting down",
                    }
            except DeadlineExceeded as e:
                return "expired", {
                    "id": rid, "ok": False, "error": "DeadlineExceeded",
                    "message": str(e),
                }
            except BaseException as e:  # noqa: BLE001 — typed to client
                return "failed", {
                    "id": rid, "ok": False, "error": "Failed",
                    "message": f"{type(e).__name__}: {e}",
                }

    def _write(self, sock, payload: Optional[Dict[str, Any]]) -> bool:
        if payload is None:
            return False
        try:
            sock.settimeout(self.conn_deadline_s)
            sock.sendall(json.dumps(payload).encode() + b"\n")
            return True
        except (OSError, socket.timeout):
            return False
