"""Load generator for the serving stack: seeded closed- and open-loop
arrival patterns, client-side latency histograms, and the backoff-retry
client convention for Overloaded sheds.

Two canonical patterns (MLPerf-inference vocabulary):

- **closed loop** — ``concurrency`` synchronous clients, each submitting
  its next request the moment the previous one completes. Measures
  sustainable throughput: the offered load self-regulates to the
  service rate, so at sub-capacity sizing the shed rate must be 0.
  Sheds are retried with resilience.retry.RetryPolicy's seeded, capped
  exponential backoff (the house client convention).
- **open loop** — Poisson arrivals at ``rate`` req/s (seeded exponential
  gaps), submitted regardless of completions, like real user traffic
  that does not slow down because the server is busy. Measures latency
  under a fixed offered load — and, past capacity, exercises the shed
  path (open-loop clients do NOT retry; a shed is recorded and dropped,
  because retrying inside the generator would mutate the arrival
  process being measured).

Determinism: request payloads and arrival gaps derive from ``seed``
only, so a report is replayable bit-for-bit on the same machine state.

The **socket transport** (NetClient + run_closed_loop_net) drives the
same patterns over the network front door (serve/net.py) instead of
in-process ``submit()``: newline-delimited JSON on a persistent TCP
connection, typed outcome mapping (Overloaded/DeadlineExceeded raised
client-side from the server's error replies), per-request timeouts, and
``RetryPolicy.decorrelated(cid)`` backoff on BOTH Overloaded replies
and transport errors — the latter is what carries a client through a
kill-endpoint → supervisor-respawn window: each retry is a NEW wire
request, so wire-tier conservation balances while the killed
endpoint's in-flight requests stand journaled as ``net_failed``. The
client is also the slow-loris attacker: armed with a
``slow-loris@SEQ:MS`` ChaosMonkey it sends half a request line, stalls
MS, and records the server's reap as ``expired`` (never retried — the
server already accounted it).
"""

from __future__ import annotations

import dataclasses
import json
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from parallel_cnn_tpu.resilience.retry import RetryPolicy
from parallel_cnn_tpu.serve.batcher import (
    DeadlineExceeded,
    DynamicBatcher,
    Overloaded,
)
from parallel_cnn_tpu.utils.metrics import Histogram


@dataclasses.dataclass
class LoadgenReport:
    """What one loadgen run measured (client-side view)."""

    pattern: str
    requests: int
    completed: int
    shed: int          # Overloaded outcomes (closed loop: after retries)
    expired: int       # DeadlineExceeded outcomes
    errors: int
    seconds: float
    latency: Histogram  # submit → result, seconds, per completed request
    offered_rate: Optional[float] = None  # open loop only (req/s)

    @property
    def throughput(self) -> float:
        return self.completed / self.seconds if self.seconds > 0 else 0.0

    @property
    def shed_rate(self) -> float:
        return self.shed / self.requests if self.requests else 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "pattern": self.pattern,
            "requests": self.requests,
            "completed": self.completed,
            "shed": self.shed,
            "expired": self.expired,
            "errors": self.errors,
            "seconds": round(self.seconds, 4),
            "throughput_rps": round(self.throughput, 2),
            "shed_rate": round(self.shed_rate, 4),
            "offered_rate": self.offered_rate,
            "latency_ms": self.latency.summary(scale=1e3),
        }


def make_samples(n: int, in_shape, seed: int = 0) -> np.ndarray:
    """Deterministic request payloads: n samples of ``in_shape``."""
    rng = np.random.default_rng(seed)
    return rng.uniform(0.0, 1.0, (n, *in_shape)).astype(np.float32)


def _wait_all(futures, counters, latency, lock):
    for t_sub, fut in futures:
        try:
            fut.result(timeout=60.0)
            with lock:
                counters["completed"] += 1
            latency.record(time.monotonic() - t_sub)
        except DeadlineExceeded:
            with lock:
                counters["expired"] += 1
        except BaseException:  # noqa: BLE001 — loadgen must finish
            with lock:
                counters["errors"] += 1


def run_closed_loop(
    batcher: DynamicBatcher,
    samples: np.ndarray,
    *,
    n_requests: int,
    concurrency: int = 8,
    deadline_ms: Optional[float] = None,
    retry: Optional[RetryPolicy] = None,
    seed: int = 0,
) -> LoadgenReport:
    """``concurrency`` synchronous clients, ``n_requests`` total."""
    retry = retry or RetryPolicy(attempts=4, base_delay=0.002,
                                 max_delay=0.05, seed=seed)
    latency = Histogram()
    counters = {"completed": 0, "shed": 0, "expired": 0, "errors": 0}
    lock = threading.Lock()
    next_idx = [0]

    def client(cid: int) -> None:
        delays = list(
            dataclasses.replace(retry, seed=retry.seed + cid).delays()
        )
        while True:
            with lock:
                i = next_idx[0]
                if i >= n_requests:
                    return
                next_idx[0] += 1
            x = samples[i % len(samples)]
            t_sub = time.monotonic()
            fut = None
            for attempt in range(retry.attempts):
                try:
                    fut = batcher.submit(x, deadline_ms=deadline_ms)
                    break
                except Overloaded:
                    if attempt == retry.attempts - 1:
                        with lock:
                            counters["shed"] += 1
                    else:
                        time.sleep(delays[attempt])
            if fut is None:
                continue
            try:
                fut.result(timeout=60.0)
                with lock:
                    counters["completed"] += 1
                latency.record(time.monotonic() - t_sub)
            except DeadlineExceeded:
                with lock:
                    counters["expired"] += 1
            except BaseException:  # noqa: BLE001
                with lock:
                    counters["errors"] += 1

    threads = [
        threading.Thread(target=client, args=(c,), daemon=True)
        for c in range(concurrency)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    seconds = time.perf_counter() - t0
    return LoadgenReport(
        pattern="closed",
        requests=n_requests,
        completed=counters["completed"],
        shed=counters["shed"],
        expired=counters["expired"],
        errors=counters["errors"],
        seconds=seconds,
        latency=latency,
    )


def run_open_loop(
    batcher: DynamicBatcher,
    samples: np.ndarray,
    *,
    n_requests: int,
    rate: float,
    deadline_ms: Optional[float] = None,
    seed: int = 0,
) -> LoadgenReport:
    """Poisson arrivals at ``rate`` req/s; sheds recorded, not retried."""
    if rate <= 0:
        raise ValueError(f"rate must be > 0 req/s, got {rate}")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate, size=n_requests)
    latency = Histogram()
    counters = {"completed": 0, "shed": 0, "expired": 0, "errors": 0}
    lock = threading.Lock()
    futures: List = []

    t0 = time.perf_counter()
    next_t = time.monotonic()
    for i in range(n_requests):
        next_t += gaps[i]
        delay = next_t - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        try:
            fut = batcher.submit(
                samples[i % len(samples)], deadline_ms=deadline_ms
            )
            futures.append((time.monotonic(), fut))
        except Overloaded:
            with lock:
                counters["shed"] += 1
    _wait_all(futures, counters, latency, lock)
    seconds = time.perf_counter() - t0
    return LoadgenReport(
        pattern="open",
        requests=n_requests,
        completed=counters["completed"],
        shed=counters["shed"],
        expired=counters["expired"],
        errors=counters["errors"],
        seconds=seconds,
        latency=latency,
        offered_rate=rate,
    )


def run(
    batcher: DynamicBatcher,
    *,
    pattern: str = "closed",
    n_requests: int = 512,
    concurrency: int = 8,
    rate: float = 500.0,
    deadline_ms: Optional[float] = None,
    seed: int = 0,
    samples: Optional[np.ndarray] = None,
) -> LoadgenReport:
    """One loadgen run against a batcher; see the pattern docs above."""
    if samples is None:
        samples = make_samples(
            min(n_requests, 64), batcher.pool.handle.in_shape, seed=seed
        )
    if pattern == "closed":
        return run_closed_loop(
            batcher, samples, n_requests=n_requests, concurrency=concurrency,
            deadline_ms=deadline_ms, seed=seed,
        )
    if pattern == "open":
        return run_open_loop(
            batcher, samples, n_requests=n_requests, rate=rate,
            deadline_ms=deadline_ms, seed=seed,
        )
    raise ValueError(f"unknown pattern {pattern!r} (closed or open)")


# ---------------------------------------------------------------------------
# Socket transport: the same patterns over the network front door.
# ---------------------------------------------------------------------------


class NetTransportError(RuntimeError):
    """Connection-level failure (refused, reset, reply timeout): the
    retryable class — it is what a client sees while a killed endpoint
    is down, and what decorrelated backoff rides through a respawn."""


class NetRequestFailed(RuntimeError):
    """The server resolved the request with a typed ``Failed`` reply
    (endpoint shutting down, replica error past failover)."""


class _WireSeq:
    """Shared client-side wire-request counter: the clock the slow-loris
    chaos schedule reads, global across all clients of one run."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def next(self) -> int:
        with self._lock:
            n = self._n
            self._n += 1
            return n


class NetClient:
    """One synchronous NDJSON client over a persistent TCP connection.

    Lazily (re)connects, so the same client object survives an endpoint
    death: the next ``request()`` raises NetTransportError, the caller
    backs off, and a later attempt reconnects to the respawned
    listener. ``chaos`` arms the slow-loris injection (see module
    docstring); ``seq`` shares the wire-request counter across clients.
    """

    def __init__(
        self,
        address: Tuple[str, int],
        *,
        timeout_s: float = 10.0,
        chaos=None,
        seq: Optional[_WireSeq] = None,
    ):
        self.address = address
        self.timeout_s = timeout_s
        self.chaos = chaos
        self.seq = seq if seq is not None else _WireSeq()
        self._sock: Optional[socket.socket] = None
        self._buf = bytearray()
        self._rid = 0

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._buf.clear()

    def __enter__(self) -> "NetClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            try:
                self._sock = socket.create_connection(
                    self.address, timeout=self.timeout_s
                )
            except OSError as e:
                raise NetTransportError(f"connect {self.address}: {e}") from e
            self._buf.clear()
        return self._sock

    def _send_loris(self, sock: socket.socket, line: bytes,
                    stall_ms: float) -> None:
        """The attack: half a request line, then a stall longer than the
        server's read deadline. The server MUST reap us — if instead the
        tail of the line is accepted after the stall, the read deadline
        is broken (and the scenario gate will see a completion where it
        required an expiry)."""
        half = max(1, len(line) // 2)
        sock.sendall(line[:half])
        time.sleep(stall_ms / 1e3)
        try:
            sock.sendall(line[half:])
            self._read_reply(sock)  # a reply here means we were NOT reaped
        except (OSError, NetTransportError):
            pass  # reaped: connection closed under us, as designed
        finally:
            self.close()

    def _read_reply(self, sock: socket.socket) -> Dict[str, Any]:
        while True:
            nl = self._buf.find(b"\n")
            if nl >= 0:
                line = bytes(self._buf[:nl])
                del self._buf[:nl + 1]
                return json.loads(line)
            try:
                chunk = sock.recv(65536)
            except (socket.timeout, OSError) as e:
                self.close()
                raise NetTransportError(f"reply read: {e}") from e
            if not chunk:
                self.close()
                raise NetTransportError("connection closed awaiting reply")
            self._buf.extend(chunk)

    def request(
        self,
        x,
        *,
        deadline_ms: Optional[float] = None,
        priority: Optional[str] = None,
    ) -> np.ndarray:
        """One wire request; raises the typed outcome:
        Overloaded / DeadlineExceeded (server-typed replies, mirrors of
        the in-process submit contract), NetRequestFailed, or
        NetTransportError (retryable). A slow-loris injection raises
        DeadlineExceeded — the server reaped it as expired."""
        from parallel_cnn_tpu.serve.net import encode_request

        wire_seq = self.seq.next()
        self._rid += 1
        line = encode_request(self._rid, x, deadline_ms, priority)
        sock = self._connect()
        stall_ms = (
            self.chaos.slow_loris_at(wire_seq)
            if self.chaos is not None else None
        )
        if stall_ms is not None:
            self._send_loris(sock, line, stall_ms)
            raise DeadlineExceeded(
                f"slow-loris@{wire_seq}: reaped by read deadline"
            )
        try:
            sock.settimeout(self.timeout_s)
            sock.sendall(line)
        except OSError as e:
            self.close()
            raise NetTransportError(f"send: {e}") from e
        reply = self._read_reply(sock)
        if reply.get("ok"):
            return np.asarray(reply["y"], dtype=np.float32)
        error = reply.get("error", "Failed")
        message = reply.get("message", "")
        if error == "Overloaded":
            raise Overloaded(message)
        if error == "DeadlineExceeded":
            raise DeadlineExceeded(message)
        raise NetRequestFailed(f"{error}: {message}")


def run_closed_loop_net(
    address: Tuple[str, int],
    samples: np.ndarray,
    *,
    n_requests: int,
    concurrency: int = 4,
    deadline_ms: Optional[float] = None,
    retry: Optional[RetryPolicy] = None,
    timeout_s: float = 10.0,
    seed: int = 0,
    chaos=None,
    on_request: Optional[Any] = None,
) -> LoadgenReport:
    """Closed loop over the wire: ``concurrency`` NetClients, each with
    a ``retry.decorrelated(cid)`` backoff stream covering Overloaded
    replies AND transport errors (the respawn-riding path). Slow-loris
    injections and server-typed deadline replies count ``expired`` and
    are never retried. ``on_request(global_index)`` — when given — is
    called before each request (the scenario hook that triggers a
    mid-run hot swap at a chosen point in the traffic)."""
    retry = retry or RetryPolicy(attempts=6, base_delay=0.01,
                                 max_delay=0.5, seed=seed)
    latency = Histogram()
    counters = {"completed": 0, "shed": 0, "expired": 0, "errors": 0}
    lock = threading.Lock()
    next_idx = [0]
    seq = _WireSeq()

    def client(cid: int) -> None:
        delays = list(retry.decorrelated(cid).delays())
        with NetClient(address, timeout_s=timeout_s, chaos=chaos,
                       seq=seq) as nc:
            while True:
                with lock:
                    i = next_idx[0]
                    if i >= n_requests:
                        return
                    next_idx[0] += 1
                if on_request is not None:
                    on_request(i)
                x = samples[i % len(samples)]
                t_sub = time.monotonic()
                outcome = None
                for attempt in range(retry.attempts):
                    try:
                        nc.request(x, deadline_ms=deadline_ms)
                        outcome = "completed"
                        latency.record(time.monotonic() - t_sub)
                        break
                    except DeadlineExceeded:
                        outcome = "expired"
                        break
                    except Overloaded:
                        outcome = "shed"
                    except (NetTransportError, NetRequestFailed):
                        outcome = "errors"
                    if attempt < retry.attempts - 1:
                        time.sleep(delays[attempt])
                with lock:
                    counters[outcome] += 1

    threads = [
        threading.Thread(target=client, args=(c,), daemon=True)
        for c in range(concurrency)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    seconds = time.perf_counter() - t0
    return LoadgenReport(
        pattern="closed-net",
        requests=n_requests,
        completed=counters["completed"],
        shed=counters["shed"],
        expired=counters["expired"],
        errors=counters["errors"],
        seconds=seconds,
        latency=latency,
    )
