"""Load generator for the serving stack: seeded closed- and open-loop
arrival patterns, client-side latency histograms, and the backoff-retry
client convention for Overloaded sheds.

Two canonical patterns (MLPerf-inference vocabulary):

- **closed loop** — ``concurrency`` synchronous clients, each submitting
  its next request the moment the previous one completes. Measures
  sustainable throughput: the offered load self-regulates to the
  service rate, so at sub-capacity sizing the shed rate must be 0.
  Sheds are retried with resilience.retry.RetryPolicy's seeded, capped
  exponential backoff (the house client convention).
- **open loop** — Poisson arrivals at ``rate`` req/s (seeded exponential
  gaps), submitted regardless of completions, like real user traffic
  that does not slow down because the server is busy. Measures latency
  under a fixed offered load — and, past capacity, exercises the shed
  path (open-loop clients do NOT retry; a shed is recorded and dropped,
  because retrying inside the generator would mutate the arrival
  process being measured).

Determinism: request payloads and arrival gaps derive from ``seed``
only, so a report is replayable bit-for-bit on the same machine state.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from parallel_cnn_tpu.resilience.retry import RetryPolicy
from parallel_cnn_tpu.serve.batcher import (
    DeadlineExceeded,
    DynamicBatcher,
    Overloaded,
)
from parallel_cnn_tpu.utils.metrics import Histogram


@dataclasses.dataclass
class LoadgenReport:
    """What one loadgen run measured (client-side view)."""

    pattern: str
    requests: int
    completed: int
    shed: int          # Overloaded outcomes (closed loop: after retries)
    expired: int       # DeadlineExceeded outcomes
    errors: int
    seconds: float
    latency: Histogram  # submit → result, seconds, per completed request
    offered_rate: Optional[float] = None  # open loop only (req/s)

    @property
    def throughput(self) -> float:
        return self.completed / self.seconds if self.seconds > 0 else 0.0

    @property
    def shed_rate(self) -> float:
        return self.shed / self.requests if self.requests else 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "pattern": self.pattern,
            "requests": self.requests,
            "completed": self.completed,
            "shed": self.shed,
            "expired": self.expired,
            "errors": self.errors,
            "seconds": round(self.seconds, 4),
            "throughput_rps": round(self.throughput, 2),
            "shed_rate": round(self.shed_rate, 4),
            "offered_rate": self.offered_rate,
            "latency_ms": self.latency.summary(scale=1e3),
        }


def make_samples(n: int, in_shape, seed: int = 0) -> np.ndarray:
    """Deterministic request payloads: n samples of ``in_shape``."""
    rng = np.random.default_rng(seed)
    return rng.uniform(0.0, 1.0, (n, *in_shape)).astype(np.float32)


def _wait_all(futures, counters, latency, lock):
    for t_sub, fut in futures:
        try:
            fut.result(timeout=60.0)
            with lock:
                counters["completed"] += 1
            latency.record(time.monotonic() - t_sub)
        except DeadlineExceeded:
            with lock:
                counters["expired"] += 1
        except BaseException:  # noqa: BLE001 — loadgen must finish
            with lock:
                counters["errors"] += 1


def run_closed_loop(
    batcher: DynamicBatcher,
    samples: np.ndarray,
    *,
    n_requests: int,
    concurrency: int = 8,
    deadline_ms: Optional[float] = None,
    retry: Optional[RetryPolicy] = None,
    seed: int = 0,
) -> LoadgenReport:
    """``concurrency`` synchronous clients, ``n_requests`` total."""
    retry = retry or RetryPolicy(attempts=4, base_delay=0.002,
                                 max_delay=0.05, seed=seed)
    latency = Histogram()
    counters = {"completed": 0, "shed": 0, "expired": 0, "errors": 0}
    lock = threading.Lock()
    next_idx = [0]

    def client(cid: int) -> None:
        delays = list(
            dataclasses.replace(retry, seed=retry.seed + cid).delays()
        )
        while True:
            with lock:
                i = next_idx[0]
                if i >= n_requests:
                    return
                next_idx[0] += 1
            x = samples[i % len(samples)]
            t_sub = time.monotonic()
            fut = None
            for attempt in range(retry.attempts):
                try:
                    fut = batcher.submit(x, deadline_ms=deadline_ms)
                    break
                except Overloaded:
                    if attempt == retry.attempts - 1:
                        with lock:
                            counters["shed"] += 1
                    else:
                        time.sleep(delays[attempt])
            if fut is None:
                continue
            try:
                fut.result(timeout=60.0)
                with lock:
                    counters["completed"] += 1
                latency.record(time.monotonic() - t_sub)
            except DeadlineExceeded:
                with lock:
                    counters["expired"] += 1
            except BaseException:  # noqa: BLE001
                with lock:
                    counters["errors"] += 1

    threads = [
        threading.Thread(target=client, args=(c,), daemon=True)
        for c in range(concurrency)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    seconds = time.perf_counter() - t0
    return LoadgenReport(
        pattern="closed",
        requests=n_requests,
        completed=counters["completed"],
        shed=counters["shed"],
        expired=counters["expired"],
        errors=counters["errors"],
        seconds=seconds,
        latency=latency,
    )


def run_open_loop(
    batcher: DynamicBatcher,
    samples: np.ndarray,
    *,
    n_requests: int,
    rate: float,
    deadline_ms: Optional[float] = None,
    seed: int = 0,
) -> LoadgenReport:
    """Poisson arrivals at ``rate`` req/s; sheds recorded, not retried."""
    if rate <= 0:
        raise ValueError(f"rate must be > 0 req/s, got {rate}")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate, size=n_requests)
    latency = Histogram()
    counters = {"completed": 0, "shed": 0, "expired": 0, "errors": 0}
    lock = threading.Lock()
    futures: List = []

    t0 = time.perf_counter()
    next_t = time.monotonic()
    for i in range(n_requests):
        next_t += gaps[i]
        delay = next_t - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        try:
            fut = batcher.submit(
                samples[i % len(samples)], deadline_ms=deadline_ms
            )
            futures.append((time.monotonic(), fut))
        except Overloaded:
            with lock:
                counters["shed"] += 1
    _wait_all(futures, counters, latency, lock)
    seconds = time.perf_counter() - t0
    return LoadgenReport(
        pattern="open",
        requests=n_requests,
        completed=counters["completed"],
        shed=counters["shed"],
        expired=counters["expired"],
        errors=counters["errors"],
        seconds=seconds,
        latency=latency,
        offered_rate=rate,
    )


def run(
    batcher: DynamicBatcher,
    *,
    pattern: str = "closed",
    n_requests: int = 512,
    concurrency: int = 8,
    rate: float = 500.0,
    deadline_ms: Optional[float] = None,
    seed: int = 0,
    samples: Optional[np.ndarray] = None,
) -> LoadgenReport:
    """One loadgen run against a batcher; see the pattern docs above."""
    if samples is None:
        samples = make_samples(
            min(n_requests, 64), batcher.pool.handle.in_shape, seed=seed
        )
    if pattern == "closed":
        return run_closed_loop(
            batcher, samples, n_requests=n_requests, concurrency=concurrency,
            deadline_ms=deadline_ms, seed=seed,
        )
    if pattern == "open":
        return run_open_loop(
            batcher, samples, n_requests=n_requests, rate=rate,
            deadline_ms=deadline_ms, seed=seed,
        )
    raise ValueError(f"unknown pattern {pattern!r} (closed or open)")
