"""Dynamic batcher: bounded queue → coalesce → bucket-pad → split.

Queueing model (docs/serving.md has the math):

- ``submit`` is non-blocking. A full bounded queue sheds the request
  with the typed ``Overloaded`` error — graceful degradation under
  overload (the client retries with resilience/retry.py backoff, or
  drops); the alternative (unbounded queue) converts overload into
  unbounded latency AND host OOM.
- The worker thread pops the oldest request, then coalesces followers
  until ``max_batch`` requests OR ``max_wait_ms`` since the first pop —
  whichever first. max_wait_ms is therefore the batching latency tax an
  idle-period request pays, and the knob that trades p50 latency for
  batch occupancy at load.
- Requests carry optional deadlines; ones already past their deadline at
  dispatch time are dropped with ``DeadlineExceeded`` instead of wasting
  a device slot on an answer nobody is waiting for.
- The dispatched batch pads into the engine's power-of-two bucket and
  the result rows are split back per request. Dispatch goes through a
  pool of ``n_replicas`` runner threads, so while replica 0 computes,
  the worker is already coalescing (and dispatching to replica 1) —
  that concurrency is what turns replica sharding into throughput.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Any, List, Optional

import numpy as np

from parallel_cnn_tpu import obs as obs_lib
from parallel_cnn_tpu.serve.engine import ReplicaDead
from parallel_cnn_tpu.serve.telemetry import ServeStats


class Overloaded(RuntimeError):
    """Request shed: the bounded request queue is full (backpressure).

    Clients should back off and retry (resilience.retry.RetryPolicy is
    the house convention — seeded, capped exponential delays) or degrade;
    the server stays healthy instead of queueing without bound."""


class DeadlineExceeded(RuntimeError):
    """Request dropped: its deadline passed before dispatch."""


class Future:
    """Minimal single-result future resolved by the batcher."""

    def __init__(self):
        self._event = threading.Event()
        self._value: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        # Observability: which replica served it, in which batch (set at
        # dispatch; None if the request died before reaching a device).
        self.replica: Optional[int] = None
        self.batch_seq: Optional[int] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("request still in flight")
        if self._error is not None:
            raise self._error
        return self._value

    def _resolve(self, value: np.ndarray) -> None:
        self._value = value
        self._event.set()

    def _fail(self, err: BaseException) -> None:
        self._error = err
        self._event.set()


class _Request:
    __slots__ = ("x", "deadline", "t_submit", "future")

    def __init__(self, x, deadline, t_submit):
        self.x = x
        self.deadline = deadline  # absolute monotonic seconds, or None
        self.t_submit = t_submit
        self.future = Future()


class DynamicBatcher:
    """Request front-end over an engine.ReplicaPool.

    ``start=False`` builds the batcher with the worker paused — tests
    use it to stage the queue deterministically (fill, overload, expire)
    before a single batch is formed — call ``start()`` to begin serving.
    Context-manager use closes the batcher (drains nothing: in-flight
    futures fail with RuntimeError on close).
    """

    def __init__(
        self,
        pool,
        *,
        max_wait_ms: float = 2.0,
        queue_depth: int = 256,
        deadline_ms: float = 0.0,
        stats: Optional[ServeStats] = None,
        start: bool = True,
        obs: Optional["obs_lib.Obs"] = None,
        chaos=None,
    ):
        self.pool = pool
        # Fault injector (resilience.chaos.ChaosMonkey): kill_replica_at
        # fires on the dispatch batch sequence number, killing the target
        # replica the instant before its predict — the mid-traffic death
        # the failover path exists for.
        self.chaos = chaos
        self.max_batch = pool.max_batch
        self.max_wait_s = max_wait_ms / 1e3
        self.default_deadline_s = deadline_ms / 1e3 if deadline_ms else None
        self.stats = stats if stats is not None else ServeStats()
        # Host-side observability hooks (spans around dispatch, request
        # lifecycle journal events); the default no-op bundle is free.
        self.obs = obs if obs is not None else obs_lib.NOOP
        self._queue: "queue_mod.Queue[_Request]" = queue_mod.Queue(
            maxsize=queue_depth
        )
        self._stop = threading.Event()
        self._batch_seq = 0
        self._runners = [
            threading.Thread(
                target=self._runner_loop, name=f"serve-runner-{i}", daemon=True
            )
            for i in range(pool.n_replicas)
        ]
        # Dispatch queue: formed batches awaiting a runner. Bounded at
        # the runner count so the worker blocks forming batch k+n until
        # a replica frees up — keeping requests in the REQUEST queue
        # (where shedding and deadline drops see them) instead of
        # accumulating in a hidden second queue.
        self._dispatch: "queue_mod.Queue" = queue_mod.Queue(
            maxsize=max(pool.n_replicas, 1)
        )
        self._worker = threading.Thread(
            target=self._worker_loop, name="serve-batcher", daemon=True
        )
        self._started = False
        if start:
            self.start()

    # -- client surface -------------------------------------------------

    def submit(self, x, deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request (a single sample, shape == in_shape).

        Raises Overloaded immediately when the bounded queue is full.
        ``deadline_ms`` is a per-request budget from now (overrides the
        batcher default; None keeps the default, 0 disables)."""
        x = np.asarray(x, dtype=np.float32)
        if x.shape != tuple(self.pool.handle.in_shape):
            raise ValueError(
                f"expected a single sample of shape "
                f"{tuple(self.pool.handle.in_shape)}, got {x.shape}"
            )
        now = time.monotonic()
        if deadline_ms is None:
            deadline = (
                now + self.default_deadline_s
                if self.default_deadline_s
                else None
            )
        else:
            deadline = now + deadline_ms / 1e3 if deadline_ms else None
        req = _Request(x, deadline, now)
        self.stats.on_submit()
        if self.obs.enabled:
            self.obs.event("submit", req=id(req.future))
            self.obs.tracer.begin_async("request", id(req.future))
        try:
            self._queue.put_nowait(req)
        except queue_mod.Full:
            self.stats.on_shed()
            if self.obs.enabled:
                self.obs.event("shed", req=id(req.future))
                self.obs.tracer.end_async("request", id(req.future))
            raise Overloaded(
                f"request queue full ({self._queue.maxsize} deep); "
                "back off and retry"
            ) from None
        return req.future

    def start(self) -> None:
        if self._started:
            return
        # graftcheck: disable=lock-discipline -- start() is single-caller by contract (constructor or the test that staged start=False)
        self._started = True
        for t in self._runners:
            t.start()
        self._worker.start()

    def close(self) -> None:
        self._stop.set()
        if self._started:
            self._worker.join(timeout=5)
            for t in self._runners:
                t.join(timeout=5)
        # Fail anything still queued so no client blocks forever.
        for q in (self._queue, self._dispatch):
            while True:
                try:
                    item = q.get_nowait()
                except queue_mod.Empty:
                    break
                reqs = item if isinstance(item, list) else [item]
                for r in reqs:
                    if isinstance(r, _Request) and not r.future.done():
                        r.future._fail(RuntimeError("batcher closed"))

    def __enter__(self) -> "DynamicBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- worker side ----------------------------------------------------

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.05)
            except queue_mod.Empty:
                continue
            batch = [first]
            t0 = time.monotonic()
            with self.obs.span("serve.coalesce", cat="serve"):
                while len(batch) < self.max_batch:
                    remaining = t0 + self.max_wait_s - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        batch.append(self._queue.get(timeout=remaining))
                    except queue_mod.Empty:
                        break
            now = time.monotonic()
            live: List[_Request] = []
            n_expired = 0
            for r in batch:
                if r.deadline is not None and now > r.deadline:
                    r.future._fail(DeadlineExceeded(
                        f"deadline passed {1e3 * (now - r.deadline):.1f} ms "
                        "before dispatch"
                    ))
                    n_expired += 1
                    if self.obs.enabled:
                        self.obs.event("expired", req=id(r.future))
                        self.obs.tracer.end_async("request", id(r.future))
                else:
                    live.append(r)
            if n_expired:
                self.stats.on_expired(n_expired)
            if not live:
                continue
            replica = self.pool.next_replica()
            seq = self._batch_seq
            # graftcheck: disable=lock-discipline -- _batch_seq is read and written only by this single worker thread
            self._batch_seq += 1
            bucket = self.pool.engines[replica].bucket_for(len(live))
            self.stats.on_batch(
                n=len(live),
                bucket=bucket,
                replica=replica,
                queue_depth=self._queue.qsize(),
            )
            if self.obs.enabled:
                self.obs.event(
                    "batch", seq=seq, n=len(live), bucket=bucket,
                    replica=replica, expired=n_expired,
                )
            # Blocks when all runners are busy — deliberate backpressure
            # (see _dispatch's bound). Bail out on close.
            while not self._stop.is_set():
                try:
                    self._dispatch.put((live, replica, seq), timeout=0.05)
                    break
                except queue_mod.Full:
                    continue

    def _runner_loop(self) -> None:
        while not self._stop.is_set():
            try:
                live, replica, seq = self._dispatch.get(timeout=0.05)
            except queue_mod.Empty:
                continue
            self._run_batch(live, replica, seq)

    def _run_batch(self, live: List[_Request], replica: int, seq: int) -> None:
        if self.chaos is not None and self.chaos.kill_replica_at(seq):
            # Chaos: the replica dies the instant before its predict —
            # the dispatch already committed to it, so the failure is
            # observed exactly where a real mid-traffic device loss
            # would surface (predict raises ReplicaDead).
            self.pool.kill(replica)
        try:
            with self.obs.span(
                "serve.batch", cat="serve",
                seq=seq, replica=replica, n=len(live),
            ):
                self._resolve_batch(live, replica, seq)
        except ReplicaDead:
            self._failover(live, replica, seq)
        except BaseException as e:  # noqa: BLE001 — forwarded to clients
            self._fail_batch(live, seq, e)

    def _resolve_batch(self, live: List[_Request], replica: int,
                       seq: int) -> None:
        """Predict + resolve, the single dispatch site — _run_batch's
        normal path and _failover's retry both land here. ReplicaDead
        propagates to the caller BEFORE any future resolves (the predict
        raises up front), so a retried batch is still whole."""
        xs = np.stack([r.x for r in live])
        ys, _ = self.pool.predict(xs, replica=replica)
        done = time.monotonic()
        for i, r in enumerate(live):
            r.future.replica = replica
            r.future.batch_seq = seq
            r.future._resolve(ys[i])
            self.stats.on_complete(done - r.t_submit)
            if self.obs.enabled:
                self.obs.event(
                    "complete", req=id(r.future), seq=seq,
                    replica=replica,
                    latency_ms=1e3 * (done - r.t_submit),
                )
                self.obs.tracer.end_async("request", id(r.future))

    def _fail_batch(self, live: List[_Request], seq: int,
                    e: BaseException) -> None:
        """The historic fail-all contract: every request in the batch
        resolves exactly once, with the error, and is counted failed."""
        self.stats.on_failed(len(live))
        for r in live:
            if not r.future.done():
                r.future._fail(e)
            if self.obs.enabled:
                self.obs.event("failed", req=id(r.future), seq=seq)
                self.obs.tracer.end_async("request", id(r.future))

    def _failover(self, live: List[_Request], dead: int, seq: int) -> None:
        """Replica ``dead`` died with this batch in flight: evict it,
        retry the still-within-deadline requests on a survivor, and
        re-pin a replacement.

        Conservation holds across the detour — every request in ``live``
        resolves exactly once: completed (retry landed), expired (its
        deadline passed before the retry could dispatch), or failed (the
        retry itself failed / no survivor was available)."""
        self.pool.evict(dead)
        if self.obs.enabled:
            self.obs.event("replica_evicted", replica=dead, seq=seq)
        now = time.monotonic()
        retry: List[_Request] = []
        n_expired = 0
        for r in live:
            if r.deadline is not None and now > r.deadline:
                r.future._fail(DeadlineExceeded(
                    f"deadline passed "
                    f"{1e3 * (now - r.deadline):.1f} ms into replica "
                    f"failover"
                ))
                n_expired += 1
                if self.obs.enabled:
                    self.obs.event("expired", req=id(r.future))
                    self.obs.tracer.end_async("request", id(r.future))
            else:
                retry.append(r)
        if n_expired:
            self.stats.on_expired(n_expired)
        respawned = False
        try:
            if retry:
                try:
                    survivor = self.pool.next_replica()
                except ReplicaDead:
                    # Single-replica pool (or total loss): the
                    # replacement IS the survivor.
                    survivor = self.pool.respawn(dead)
                    respawned = True
                    if self.obs.enabled:
                        self.obs.event(
                            "replica_respawned", replica=dead, seq=seq
                        )
                if self.obs.enabled:
                    self.obs.event(
                        "failover", seq=seq, dead=dead,
                        survivor=survivor, n=len(retry),
                        expired=n_expired,
                    )
                self._resolve_batch(retry, survivor, seq)
        except BaseException as e:  # noqa: BLE001 — forwarded to clients
            self._fail_batch(retry, seq, e)
        finally:
            if not respawned:
                self.pool.respawn(dead)
                if self.obs.enabled:
                    self.obs.event(
                        "replica_respawned", replica=dead, seq=seq
                    )


def serve_stack(
    handle,
    cfg,
    *,
    devices=None,
    stats: Optional[ServeStats] = None,
    start: bool = True,
    obs: Optional["obs_lib.Obs"] = None,
    chaos=None,
):
    """(pool, batcher) wired from a config.ServeConfig — the one-call
    constructor the CLI, benches, and dryrun share. ``chaos`` (a
    resilience.chaos.ChaosMonkey) arms kill-replica fault injection."""
    from parallel_cnn_tpu.serve.engine import ReplicaPool

    pool = ReplicaPool(
        handle,
        n_replicas=cfg.n_replicas,
        checkpoint=cfg.checkpoint,
        max_batch=cfg.max_batch,
        devices=devices,
        precompile=cfg.precompile,
        obs=obs,
    )
    batcher = DynamicBatcher(
        pool,
        max_wait_ms=cfg.max_wait_ms,
        queue_depth=cfg.queue_depth,
        deadline_ms=cfg.deadline_ms,
        stats=stats,
        start=start,
        obs=obs,
        chaos=chaos,
    )
    return pool, batcher
