"""Dynamic batcher: bounded queue → coalesce → bucket-pad → split.

Queueing model (docs/serving.md has the math):

- ``submit`` is non-blocking. A full bounded queue sheds the request
  with the typed ``Overloaded`` error — graceful degradation under
  overload (the client retries with resilience/retry.py backoff, or
  drops); the alternative (unbounded queue) converts overload into
  unbounded latency AND host OOM.
- The worker thread pops the oldest request, then coalesces followers
  until ``max_batch`` requests OR ``max_wait_ms`` since the first pop —
  whichever first. max_wait_ms is therefore the batching latency tax an
  idle-period request pays, and the knob that trades p50 latency for
  batch occupancy at load.
- Requests carry optional deadlines; overdue ones are dropped with
  ``DeadlineExceeded`` the moment the worker pops them (the coalesce-time
  sweep — under backlog an expired request frees its queue slot
  immediately instead of riding along to dispatch), with a second sweep
  at dispatch time as the final check before a device slot is spent.
- An optional admission controller (serve/admission.py) runs in front of
  the queue: ``submit`` consults it before enqueueing (predicted-late and
  degradation-ladder rejects surface as ``Overloaded`` and count as
  sheds), and the worker lets it shrink the coalescing window / cap the
  bucket under pressure. The batcher feeds queue-wait and service-time
  observations back so the controller's EWMA predictor tracks reality.
- The dispatched batch pads into the engine's power-of-two bucket and
  the result rows are split back per request. Dispatch goes through a
  pool of ``n_replicas`` runner threads, so while replica 0 computes,
  the worker is already coalescing (and dispatching to replica 1) —
  that concurrency is what turns replica sharding into throughput.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Any, List, Optional

import numpy as np

from parallel_cnn_tpu import obs as obs_lib
from parallel_cnn_tpu.serve.engine import ReplicaDead
from parallel_cnn_tpu.serve.telemetry import ServeStats


class Overloaded(RuntimeError):
    """Request shed: the bounded request queue is full (backpressure).

    Clients should back off and retry (resilience.retry.RetryPolicy is
    the house convention — seeded, capped exponential delays) or degrade;
    the server stays healthy instead of queueing without bound."""


class DeadlineExceeded(RuntimeError):
    """Request dropped: its deadline passed before dispatch."""


class Future:
    """Minimal single-result future resolved by the batcher."""

    def __init__(self):
        self._event = threading.Event()
        self._value: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        # Observability: which replica served it, in which batch (set at
        # dispatch; None if the request died before reaching a device).
        self.replica: Optional[int] = None
        self.batch_seq: Optional[int] = None
        # Resolution instant (monotonic), so callers polling result()
        # later can still measure true latency instead of observe time.
        self.t_done: Optional[float] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("request still in flight")
        if self._error is not None:
            raise self._error
        return self._value

    def _resolve(self, value: np.ndarray) -> None:
        self._value = value
        self.t_done = time.monotonic()
        self._event.set()

    def _fail(self, err: BaseException) -> None:
        self._error = err
        self.t_done = time.monotonic()
        self._event.set()


class _Request:
    __slots__ = ("x", "deadline", "t_submit", "priority", "future")

    def __init__(self, x, deadline, t_submit, priority="guaranteed"):
        self.x = x
        self.deadline = deadline  # absolute monotonic seconds, or None
        self.t_submit = t_submit
        self.priority = priority  # "guaranteed" | "best-effort"
        self.future = Future()


class DynamicBatcher:
    """Request front-end over an engine.ReplicaPool.

    ``start=False`` builds the batcher with the worker paused — tests
    use it to stage the queue deterministically (fill, overload, expire)
    before a single batch is formed — call ``start()`` to begin serving.
    Context-manager use closes the batcher (drains nothing: in-flight
    futures fail with RuntimeError on close).
    """

    def __init__(
        self,
        pool,
        *,
        max_wait_ms: float = 2.0,
        queue_depth: int = 256,
        deadline_ms: float = 0.0,
        stats: Optional[ServeStats] = None,
        start: bool = True,
        obs: Optional["obs_lib.Obs"] = None,
        chaos=None,
        admission=None,
    ):
        self.pool = pool
        # Fault injector (resilience.chaos.ChaosMonkey): kill_replica_at
        # fires on the dispatch batch sequence number, killing the target
        # replica the instant before its predict — the mid-traffic death
        # the failover path exists for; slow_replica_at stalls it instead
        # (the straggler the SLO gate exists to catch).
        self.chaos = chaos
        # SLO admission controller (serve/admission.py), or None for the
        # historical admit-everything-until-the-queue-is-full behavior.
        self.admission = admission
        self.max_batch = pool.max_batch
        self.max_wait_s = max_wait_ms / 1e3
        self.default_deadline_s = deadline_ms / 1e3 if deadline_ms else None
        self.stats = stats if stats is not None else ServeStats()
        # Host-side observability hooks (spans around dispatch, request
        # lifecycle journal events); the default no-op bundle is free.
        self.obs = obs if obs is not None else obs_lib.NOOP
        self._queue: "queue_mod.Queue[_Request]" = queue_mod.Queue(
            maxsize=queue_depth
        )
        self._stop = threading.Event()
        self._batch_seq = 0
        # Per-replica in-flight batch counts (formed-but-unfinished):
        # the autoscaler's drain barrier — a replica retires only after
        # its count returns to zero. Guarded by _lock.
        self._lock = threading.Lock()
        self._inflight: dict = {}
        self._runners = [
            threading.Thread(
                target=self._runner_loop, name=f"serve-runner-{i}", daemon=True
            )
            for i in range(pool.n_replicas)
        ]
        # Dispatch queue: formed batches awaiting a runner. Bounded at
        # the runner count so the worker blocks forming batch k+n until
        # a replica frees up — keeping requests in the REQUEST queue
        # (where shedding and deadline drops see them) instead of
        # accumulating in a hidden second queue.
        self._dispatch: "queue_mod.Queue" = queue_mod.Queue(
            maxsize=max(pool.n_replicas, 1)
        )
        self._worker = threading.Thread(
            target=self._worker_loop, name="serve-batcher", daemon=True
        )
        self._started = False
        if start:
            self.start()

    # -- client surface -------------------------------------------------

    def submit(self, x, deadline_ms: Optional[float] = None,
               priority: str = "guaranteed") -> Future:
        """Enqueue one request (a single sample, shape == in_shape).

        Raises Overloaded immediately when the bounded queue is full —
        or, with an admission controller attached, when the controller
        predicts the deadline cannot be met / the degradation ladder is
        shedding this priority class (both count as sheds: conservation
        is submitted == completed + shed + expired + failed).
        ``deadline_ms`` is a per-request budget from now (overrides the
        batcher default; None keeps the default, 0 disables).
        ``priority`` is "guaranteed" (default) or "best-effort" — the
        class the ladder drops first under pressure."""
        if priority not in ("guaranteed", "best-effort"):
            raise ValueError(
                f"priority must be 'guaranteed' or 'best-effort', "
                f"got {priority!r}"
            )
        x = np.asarray(x, dtype=np.float32)
        if x.shape != tuple(self.pool.handle.in_shape):
            raise ValueError(
                f"expected a single sample of shape "
                f"{tuple(self.pool.handle.in_shape)}, got {x.shape}"
            )
        now = time.monotonic()
        if deadline_ms is None:
            deadline = (
                now + self.default_deadline_s
                if self.default_deadline_s
                else None
            )
        else:
            deadline = now + deadline_ms / 1e3 if deadline_ms else None
        req = _Request(x, deadline, now, priority)
        self.stats.on_submit()
        if self.obs.enabled:
            self.obs.event("submit", req=id(req.future))
            self.obs.tracer.begin_async("request", id(req.future))
        if self.admission is not None:
            reason = self.admission.admit(
                priority=priority, deadline=deadline, now=now,
                queue_depth=self._queue.qsize(),
            )
            if reason is not None:
                self.stats.on_shed()
                if self.obs.enabled:
                    self.obs.event("shed", req=id(req.future),
                                   reason=reason)
                    self.obs.tracer.end_async("request", id(req.future))
                raise Overloaded(f"admission rejected: {reason}; "
                                 "back off and retry")
        try:
            self._queue.put_nowait(req)
        except queue_mod.Full:
            self.stats.on_shed()
            if self.obs.enabled:
                self.obs.event("shed", req=id(req.future))
                self.obs.tracer.end_async("request", id(req.future))
            raise Overloaded(
                f"request queue full ({self._queue.maxsize} deep); "
                "back off and retry"
            ) from None
        return req.future

    def start(self) -> None:
        if self._started:
            return
        # graftcheck: disable=lock-discipline -- start() is single-caller by contract (constructor or the test that staged start=False)
        self._started = True
        for t in self._runners:
            t.start()
        self._worker.start()

    def close(self) -> None:
        self._stop.set()
        if self._started:
            self._worker.join(timeout=5)
            for t in self._runners:
                t.join(timeout=5)
        # Fail anything still queued so no client blocks forever.
        for q in (self._queue, self._dispatch):
            while True:
                try:
                    item = q.get_nowait()
                except queue_mod.Empty:
                    break
                reqs = item if isinstance(item, list) else [item]
                for r in reqs:
                    if isinstance(r, _Request) and not r.future.done():
                        r.future._fail(RuntimeError("batcher closed"))

    def __enter__(self) -> "DynamicBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- worker side ----------------------------------------------------

    def _expire_req(self, r: _Request, now: float, where: str) -> None:
        """Fail one overdue request (coalesce- or dispatch-time sweep);
        the caller already knows now > r.deadline."""
        r.future._fail(DeadlineExceeded(
            f"deadline passed {1e3 * (now - r.deadline):.1f} ms "
            f"{where}"
        ))
        self.stats.on_expired(1)
        if self.obs.enabled:
            self.obs.event("expired", req=id(r.future))
            self.obs.tracer.end_async("request", id(r.future))

    def _pop_live(self, timeout: float) -> Optional[_Request]:
        """Pop one request, expiring overdue ones immediately (the
        coalesce-time sweep): under backlog a dead request frees its
        queue slot the moment the worker sees it, instead of riding
        along to dispatch. Returns None on timeout."""
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            try:
                r = self._queue.get(timeout=max(remaining, 0.0))
            except queue_mod.Empty:
                return None
            now = time.monotonic()
            if r.deadline is not None and now > r.deadline:
                self._expire_req(r, now, "in queue (coalesce sweep)")
                continue
            return r

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            first = self._pop_live(timeout=0.05)
            if first is None:
                continue
            # The degradation ladder (admission controller) may shrink
            # the coalescing window and cap the bucket under pressure.
            wait_s = self.max_wait_s
            cap = self.max_batch
            if self.admission is not None:
                wait_s = self.admission.effective_wait_s(wait_s)
                cap = self.admission.effective_max_batch(cap)
            batch = [first]
            t0 = time.monotonic()
            with self.obs.span("serve.coalesce", cat="serve"):
                while len(batch) < cap:
                    remaining = t0 + wait_s - time.monotonic()
                    if remaining <= 0:
                        break
                    r = self._pop_live(timeout=remaining)
                    if r is None:
                        break
                    batch.append(r)
            now = time.monotonic()
            live: List[_Request] = []
            n_expired = 0
            for r in batch:
                if r.deadline is not None and now > r.deadline:
                    self._expire_req(r, now, "before dispatch")
                    n_expired += 1
                else:
                    live.append(r)
            if not live:
                continue
            replica = self.pool.next_replica()
            seq = self._batch_seq
            # graftcheck: disable=lock-discipline -- _batch_seq is read and written only by this single worker thread
            self._batch_seq += 1
            bucket = self.pool.engines[replica].bucket_for(len(live))
            self.stats.on_batch(
                n=len(live),
                bucket=bucket,
                replica=replica,
                queue_depth=self._queue.qsize(),
            )
            if self.admission is not None:
                self.admission.observe_queue_wait(
                    max(now - r.t_submit for r in live)
                )
            if self.obs.enabled:
                self.obs.event(
                    "batch", seq=seq, n=len(live), bucket=bucket,
                    replica=replica, expired=n_expired,
                )
            with self._lock:
                self._inflight[replica] = self._inflight.get(replica, 0) + 1
            # Blocks when all runners are busy — deliberate backpressure
            # (see _dispatch's bound). Bail out on close.
            queued = False
            while not self._stop.is_set():
                try:
                    self._dispatch.put((live, replica, seq), timeout=0.05)
                    queued = True
                    break
                except queue_mod.Full:
                    continue
            if not queued:
                # Closing: the batch never reached a runner; close()
                # fails its futures, but the in-flight count must not
                # leak a phantom batch.
                with self._lock:
                    self._inflight[replica] -= 1

    def _runner_loop(self) -> None:
        while not self._stop.is_set():
            try:
                live, replica, seq = self._dispatch.get(timeout=0.05)
            except queue_mod.Empty:
                continue
            try:
                self._run_batch(live, replica, seq)
            finally:
                with self._lock:
                    self._inflight[replica] -= 1

    def inflight(self, replica: int) -> int:
        """Batches formed for ``replica`` and not yet finished — the
        autoscaler's drain barrier (failover retries still count against
        the ORIGINAL replica until the batch resolves, which is the
        conservative direction for a drain)."""
        with self._lock:
            return self._inflight.get(replica, 0)

    @property
    def n_runners(self) -> int:
        with self._lock:
            return len(self._runners)

    def add_runner(self) -> None:
        """Grow the runner pool by one thread (autoscaler scale-up, after
        ReplicaPool.grow appended a replica): widens the dispatch bound
        so the new replica can hold a batch in flight concurrently."""
        with self._lock:
            i = len(self._runners)
            t = threading.Thread(
                target=self._runner_loop, name=f"serve-runner-{i}",
                daemon=True,
            )
            self._runners.append(t)
            if self._started:
                t.start()
        # queue.Queue has no resize API; maxsize is guarded by the
        # queue's OWN mutex (the one put()/get() contend on), not by
        # self._lock — taking both here would order them against the
        # worker loop, which blocks in put() while holding no lock.
        with self._dispatch.mutex:
            # graftcheck: disable=lock-discipline -- maxsize belongs to the queue's own mutex, held by this with-block
            self._dispatch.maxsize += 1
            self._dispatch.not_full.notify()

    def _run_batch(self, live: List[_Request], replica: int, seq: int) -> None:
        if self.chaos is not None:
            stall_ms = self.chaos.slow_replica_at(seq)
            if stall_ms is not None:
                # Chaos: the replica straggles — the batch (and the
                # queue behind it) eats the stall, exactly the tail
                # latency the SLO gate watches.
                if self.obs.enabled:
                    self.obs.event("chaos_slow_replica", seq=seq,
                                   replica=replica, ms=stall_ms)
                time.sleep(stall_ms / 1e3)
        if self.chaos is not None and self.chaos.kill_replica_at(seq):
            # Chaos: the replica dies the instant before its predict —
            # the dispatch already committed to it, so the failure is
            # observed exactly where a real mid-traffic device loss
            # would surface (predict raises ReplicaDead).
            self.pool.kill(replica)
        try:
            with self.obs.span(
                "serve.batch", cat="serve",
                seq=seq, replica=replica, n=len(live),
            ):
                self._resolve_batch(live, replica, seq)
        except ReplicaDead:
            self._failover(live, replica, seq)
        except BaseException as e:  # noqa: BLE001 — forwarded to clients
            self._fail_batch(live, seq, e)

    def _resolve_batch(self, live: List[_Request], replica: int,
                       seq: int) -> None:
        """Predict + resolve, the single dispatch site — _run_batch's
        normal path and _failover's retry both land here. ReplicaDead
        propagates to the caller BEFORE any future resolves (the predict
        raises up front), so a retried batch is still whole."""
        xs = np.stack([r.x for r in live])
        t_exec = time.monotonic()
        ys, _ = self.pool.predict(xs, replica=replica)
        done = time.monotonic()
        if self.admission is not None:
            self.admission.observe_service(
                self.pool.engines[replica].bucket_for(len(live)),
                done - t_exec,
            )
        for i, r in enumerate(live):
            r.future.replica = replica
            r.future.batch_seq = seq
            r.future._resolve(ys[i])
            self.stats.on_complete(done - r.t_submit)
            if self.obs.enabled:
                self.obs.event(
                    "complete", req=id(r.future), seq=seq,
                    replica=replica,
                    latency_ms=1e3 * (done - r.t_submit),
                )
                self.obs.tracer.end_async("request", id(r.future))

    def _fail_batch(self, live: List[_Request], seq: int,
                    e: BaseException) -> None:
        """The historic fail-all contract: every request in the batch
        resolves exactly once, with the error, and is counted failed."""
        self.stats.on_failed(len(live))
        for r in live:
            if not r.future.done():
                r.future._fail(e)
            if self.obs.enabled:
                self.obs.event("failed", req=id(r.future), seq=seq)
                self.obs.tracer.end_async("request", id(r.future))

    def _failover(self, live: List[_Request], dead: int, seq: int) -> None:
        """Replica ``dead`` died with this batch in flight: evict it,
        retry the still-within-deadline requests on a survivor, and
        re-pin a replacement.

        Conservation holds across the detour — every request in ``live``
        resolves exactly once: completed (retry landed), expired (its
        deadline passed before the retry could dispatch), or failed (the
        retry itself failed / no survivor was available)."""
        self.pool.evict(dead)
        if self.obs.enabled:
            self.obs.event("replica_evicted", replica=dead, seq=seq)
        now = time.monotonic()
        retry: List[_Request] = []
        n_expired = 0
        for r in live:
            if r.deadline is not None and now > r.deadline:
                r.future._fail(DeadlineExceeded(
                    f"deadline passed "
                    f"{1e3 * (now - r.deadline):.1f} ms into replica "
                    f"failover"
                ))
                n_expired += 1
                if self.obs.enabled:
                    self.obs.event("expired", req=id(r.future))
                    self.obs.tracer.end_async("request", id(r.future))
            else:
                retry.append(r)
        if n_expired:
            self.stats.on_expired(n_expired)
        respawned = False
        try:
            if retry:
                try:
                    survivor = self.pool.next_replica()
                except ReplicaDead:
                    # Single-replica pool (or total loss): the
                    # replacement IS the survivor.
                    survivor = self.pool.respawn(dead)
                    respawned = True
                    if self.obs.enabled:
                        self.obs.event(
                            "replica_respawned", replica=dead, seq=seq
                        )
                if self.obs.enabled:
                    self.obs.event(
                        "failover", seq=seq, dead=dead,
                        survivor=survivor, n=len(retry),
                        expired=n_expired,
                    )
                self._resolve_batch(retry, survivor, seq)
        except BaseException as e:  # noqa: BLE001 — forwarded to clients
            self._fail_batch(retry, seq, e)
        finally:
            if not respawned:
                self.pool.respawn(dead)
                if self.obs.enabled:
                    self.obs.event(
                        "replica_respawned", replica=dead, seq=seq
                    )


def serve_stack(
    handle,
    cfg,
    *,
    devices=None,
    stats: Optional[ServeStats] = None,
    start: bool = True,
    obs: Optional["obs_lib.Obs"] = None,
    chaos=None,
    admission=None,
    cache_dir=None,
):
    """(pool, batcher) wired from a config.ServeConfig — the one-call
    constructor the CLI, benches, and dryrun share. ``chaos`` (a
    resilience.chaos.ChaosMonkey) arms kill-replica / slow-replica fault
    injection. ``admission`` overrides the controller instance; by
    default one is built when ``cfg.admission`` is set (the SLO surface
    — serve/admission.py). ``cache_dir`` enables the engines'
    persistent AOT-executable cache (config.NetConfig.aot_cache_dir)."""
    from parallel_cnn_tpu import plan as plan_lib
    from parallel_cnn_tpu.serve.engine import ReplicaPool

    # The serving ExecutionPlan (plan/): eval sharding is replicated
    # single-device, so the plan pins the compile/AOT policy, and its
    # fingerprint keys the engines' on-disk executable cache.
    splan = plan_lib.serve_plan(cfg, cache_dir=cache_dir)
    pool = ReplicaPool(
        handle,
        n_replicas=cfg.n_replicas,
        checkpoint=cfg.checkpoint,
        max_batch=cfg.max_batch,
        devices=devices,
        precompile=cfg.precompile,
        obs=obs,
        cache_dir=cache_dir,
        plan_fingerprint=splan.fingerprint(),
    )
    if admission is None and getattr(cfg, "admission", False):
        from parallel_cnn_tpu.serve.admission import AdmissionController

        admission = AdmissionController(
            slo_ms=cfg.slo_ms,
            queue_depth=cfg.queue_depth,
            obs=obs,
        )
    if stats is None:
        stats = ServeStats(window_s=getattr(cfg, "window_s", 10.0))
    batcher = DynamicBatcher(
        pool,
        max_wait_ms=cfg.max_wait_ms,
        queue_depth=cfg.queue_depth,
        deadline_ms=cfg.deadline_ms,
        stats=stats,
        start=start,
        obs=obs,
        chaos=chaos,
        admission=admission,
    )
    return pool, batcher
