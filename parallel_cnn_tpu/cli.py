"""CLI driver (≙ main(), Sequential/Main.cpp:44-57 — which accepts
argc/argv and ignores them; here the flags actually work).

    python -m parallel_cnn_tpu [--loader …] [--epochs N] [--batch-size B] …

Drives the same flow as every reference backend: load data → learn →
test, printing the reference's lines ("Learning", per-epoch error, final
error rate), plus the subsystems the reference lacks: checkpoint/resume,
structured metrics, and the per-phase profile table (paper Tables 4-8).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from typing import List, Optional

from parallel_cnn_tpu import obs as obs_lib
from parallel_cnn_tpu.config import (
    AsyncConfig,
    AutotuneConfig,
    CommConfig,
    Config,
    DataConfig,
    ElasticConfig,
    FusedStepConfig,
    MeshConfig,
    NetConfig,
    ObsConfig,
    PipelineConfig,
    ResilienceConfig,
    ServeConfig,
    TrainConfig,
    plan_path_from_env,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="parallel_cnn_tpu",
        description="TPU-native trainer with the reference's capabilities",
    )
    d, t = DataConfig(), TrainConfig()
    p.add_argument("--model", default="lenet_ref",
                   choices=["lenet_ref", "cifar_cnn", "resnet18", "resnet34",
                            "resnet50", "vgg16"],
                   help="lenet_ref = the reference-parity trainer; the rest "
                        "route to the model-zoo trainer (train/zoo.py, "
                        "synthetic CIFAR-shape data, SGD+momentum)")
    p.add_argument("--conv-backend", default="xla",
                   choices=["xla", "pallas"],
                   help="zoo models only: conv kernel library — XLA convs "
                        "or the hand-written Pallas tapped-matmul kernels "
                        "(ops/pallas_conv.py)")
    p.add_argument("--lr", type=float, default=0.1,
                   help="zoo models only: SGD learning rate")
    p.add_argument("--lr-schedule", default="constant",
                   choices=["constant", "cosine"],
                   help="zoo models only: cosine decays over the full run "
                        "(epochs x steps); both honor --warmup-steps")
    p.add_argument("--warmup-steps", type=int, default=0,
                   help="zoo models only: linear LR warmup steps")
    p.add_argument("--augment", action="store_true",
                   help="zoo models only: on-device random crop + "
                        "horizontal flip (CIFAR recipe), traced into the "
                        "train step")
    # None sentinel: the autotuner's chosen plan may fill it; unset and
    # untuned resolves to 1 (the historical no-accumulation default).
    p.add_argument("--accum-steps", type=int, default=None,
                   help="zoo models only: gradient-accumulation "
                        "microbatches (default 1; --autotune may set it)")
    p.add_argument("--zoo-loader", default="device",
                   choices=["device", "native"],
                   help="zoo models only: batch source — on-device gathers "
                        "over the HBM-resident dataset, or the native C++ "
                        "prefetch ring (data/native.py; NumPy-twin fallback "
                        "without a toolchain)")
    p.add_argument("--loader", default=d.loader,
                   choices=["auto", "native", "numpy", "synthetic"])
    p.add_argument("--data-dir", default=None,
                   help="directory holding the four idx files "
                        "(defaults to the DataConfig paths)")
    p.add_argument("--epochs", type=int, default=t.epochs)
    # None sentinel: lenet_ref defaults to the strict-parity batch_size=1,
    # zoo models to minibatch 128 — an EXPLICIT value is never reinterpreted.
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--dt", type=float, default=t.dt,
                   help="SGD step (dt at Sequential/layer.h:12)")
    p.add_argument("--threshold", type=float, default=t.threshold,
                   help="early-stop err threshold (layer.h:13)")
    p.add_argument("--seed", type=int, default=t.seed)
    p.add_argument("--shuffle", action="store_true")
    p.add_argument("--prefetch", default=t.prefetch,
                   choices=["auto", "native", "off"])
    p.add_argument("--dtype", default=t.dtype,
                   choices=["float32", "bfloat16"],
                   help="compute dtype; bfloat16 = MXU-native mixed "
                        "precision (batch_size>1 only)")
    p.add_argument("--ops", default=t.ops,
                   choices=["reference", "pallas"],
                   help="kernel library: path A (jnp/lax, XLA-fused) or "
                        "path B (hand-written Pallas/Mosaic kernels ≙ the "
                        "CUDA backend; batch_size>1 only)")
    p.add_argument("--synthetic-train-count", type=int,
                   default=d.synthetic_train_count)
    p.add_argument("--synthetic-test-count", type=int,
                   default=d.synthetic_test_count)
    p.add_argument("--mesh-data", type=int, default=None, metavar="N",
                   help="data(-parallel) mesh axis size; setting either "
                        "mesh flag routes minibatch training over the "
                        "device mesh (≙ mpirun -np N, MPI/Main.cpp:44)")
    p.add_argument("--mesh-model", type=int, default=None, metavar="N",
                   help="model (intra-op) mesh axis size. lenet_ref: must "
                        "divide the 6 conv filters (legal: 1, 2, 3, 6). "
                        "zoo models: filter/channel GSPMD sharding "
                        "(parallel/zoo_sharding.py) composed with "
                        "--mesh-data DP on the 2-D mesh")
    p.add_argument("--comm-impl", default=None,
                   choices=["psum", "ring", "hierarchical"],
                   help="mesh runs: gradient-collective algorithm "
                        "(parallel/collectives.py) — monolithic psum, "
                        "bucketed ring reduce-scatter/all-gather over the "
                        "data axis, or the two-level hierarchical ring "
                        "over a (host, device) mesh (inter-host links "
                        "carry 1/n_dev of the payload; docs/collectives.md)"
                        ". Default: PCNN_COMM_IMPL, else the "
                        "historical implicit psum/GSPMD path")
    p.add_argument("--comm-bucket-mb", type=float, default=None, metavar="MB",
                   help="ring collective bucket size in MiB "
                        "(PCNN_COMM_BUCKET_BYTES; default 4)")
    p.add_argument("--comm-wire-dtype", default=None,
                   choices=["float32", "bfloat16"],
                   help="collective payload dtype on the wire; bfloat16 "
                        "halves ICI bytes, accumulation stays f32 "
                        "(PCNN_COMM_WIRE_DTYPE)")
    p.add_argument("--comm-hosts", type=int, default=None, metavar="N",
                   help="--comm-impl hierarchical: host-axis size of the "
                        "(host, device) mesh. Default (PCNN_COMM_HOSTS "
                        "unset): derive one host row per jax.distributed "
                        "process; an explicit N splits one process's "
                        "devices into N emulated hosts (CPU testing)")
    p.add_argument("--autotune", action="store_true",
                   help="zoo mesh runs: apply the cost report's chosen "
                        "parallelism plan (analysis/autotune.py; run "
                        "`python -m parallel_cnn_tpu tune` first) as the "
                        "base layer — explicit --comm-*/--fused-step/"
                        "--pipeline-*/--accum-steps knobs still win "
                        "[PCNN_AUTOTUNE]")
    p.add_argument("--autotune-report", default=None, metavar="PATH",
                   help="cost report the chosen plan is read from "
                        "(default analysis/cost_report.json) "
                        "[PCNN_AUTOTUNE_REPORT]")
    p.add_argument("--pipeline-stages", type=int, default=None, metavar="S",
                   help="zoo mesh runs: pipeline parallelism — partition "
                        "the model's layers over S stages of a (stage, "
                        "data) mesh and run the 1F1B microbatch schedule "
                        "(train/pipeline_schedule.py; --accum-steps is "
                        "the microbatch count M). Builds its own mesh "
                        "over all devices; drop --mesh-data/--mesh-model."
                        " S=1 is the degenerate single-stage pipeline "
                        "(bit-exact vs the flat data mesh) "
                        "[PCNN_PIPELINE_STAGES]")
    p.add_argument("--pipeline-split", default=None, metavar="B1,B2,..",
                   help="manual stage boundaries (layer indices, "
                        "stages-1 of them); default: balanced split from "
                        "the analysis/cost_model.py per-layer flops "
                        "tables [PCNN_PIPELINE_SPLIT]")
    p.add_argument("--pipeline-wire-dtype", default=None,
                   choices=["float32", "bfloat16"],
                   help="dtype of the inter-stage activation/cotangent "
                        "ppermute payload; accumulation stays f32 "
                        "[PCNN_PIPELINE_WIRE_DTYPE]")
    p.add_argument("--pipeline-act-dtype", default=None,
                   choices=["float32", "bfloat16"],
                   help="stage-compute activation dtype (params cast "
                        "per-layer, grads/loss stay f32) "
                        "[PCNN_PIPELINE_ACT_DTYPE]")
    p.add_argument("--fused-step", action="store_true",
                   help="fused training step (PCNN_FUSED_STEP): fused "
                        "pool→FC→softmax-CE loss tail, bf16 activations "
                        "over f32 masters with loss scaling, and — on "
                        "zoo mesh runs with --comm-impl ring — the "
                        "update-on-arrival fused optimizer "
                        "(ops/pallas_update.py)")
    p.add_argument("--act-dtype", default=None,
                   choices=["float32", "bfloat16"],
                   help="fused-step activation dtype (PCNN_ACT_DTYPE; "
                        "default bfloat16). Refines --fused-step only — "
                        "it never enables the fused path by itself")
    p.add_argument("--plan", default=None, metavar="PATH",
                   help="execution-plan file (docs/execution_plan.md; "
                        "written by `tune --report` or `plan show --save`): "
                        "fills every parallelism knob the env and explicit "
                        "flags left unset — flag beats env beats plan "
                        "[PCNN_PLAN]")
    p.add_argument("--replan", action="store_true",
                   help="allow resuming from a checkpoint whose recorded "
                        "plan fingerprint mismatches the live plan "
                        "(re-shard under the live plan instead of refusing "
                        "with PlanMismatchError)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="save ckpt_<epoch>.npz per epoch; --resume restarts "
                        "from the latest")
    p.add_argument("--resume", action="store_true")
    r = ResilienceConfig()
    p.add_argument("--sentinel", default=r.policy,
                   choices=["off", "raise", "skip", "rollback"],
                   help="health-sentinel policy on a non-finite "
                        "loss/param: fail fast, discard the epoch, or "
                        "auto-rollback to the last-good state "
                        "(resilience/)")
    p.add_argument("--max-rollbacks", type=int, default=r.max_rollbacks,
                   help="bounded retry budget for --sentinel rollback")
    p.add_argument("--lr-backoff", type=float, default=r.lr_backoff,
                   help="LR multiplier applied per rollback "
                        "(lenet_ref path; 1.0 keeps the LR)")
    p.add_argument("--sentinel-every", type=int, default=r.check_every_steps,
                   metavar="N",
                   help="zoo models: also run the sentinel every N "
                        "optimizer steps (0 = epoch boundaries only; "
                        "each check is a host sync)")
    p.add_argument("--keep-checkpoints", type=int, default=r.ring_size,
                   metavar="N",
                   help="prune --checkpoint-dir to the newest N "
                        "checkpoints (0 = keep all)")
    p.add_argument("--no-pallas-fallback", action="store_true",
                   help="fail instead of degrading to the XLA path when "
                        "the Pallas kernel path errors")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="fault injection for resilience testing: "
                        "nan@STEP poisons the update at optimizer step "
                        "STEP; kill@EPOCH / kill9@EPOCH delivers "
                        "SIGTERM / SIGKILL after epoch EPOCH's "
                        "checkpoint; resize@STEP:±K loses/adds K devices "
                        "at optimizer step STEP (needs --elastic); "
                        "kill-replica@SEQ kills the serving replica "
                        "holding dispatch batch SEQ (serve path); "
                        "slow-replica@SEQ:MS stalls it MS ms instead "
                        "(serve path); slow-worker@STEP:MS stalls the "
                        "training worker dispatching gradient step STEP "
                        "for MS ms — the async-training straggler; "
                        "slow-stage@STEP:MS stalls the pipeline trainer "
                        "MS ms at the step-STEP dispatch boundary — the "
                        "1F1B straggler (needs --pipeline-stages) "
                        "(resilience/chaos.py has the full grammar)")
    p.add_argument("--elastic", action="store_true",
                   help="elastic training (PCNN_ELASTIC): on a preemption "
                        "resize request, a chaos resize@, or a schedule "
                        "entry, quiesce at the microbatch boundary, "
                        "snapshot the ZeRO-3 state to a world-size-"
                        "independent view, re-mesh over the surviving "
                        "devices, reshard, and continue — no disk round "
                        "trip, no restart (resilience/elastic.py). "
                        "Requires the ZeRO-3 step (--fused-step path "
                        "with zero=3 + --comm-impl ring/hierarchical)")
    p.add_argument("--elastic-schedule", default=None, metavar="SPEC",
                   help="planned resizes 'STEP:WORLD[,STEP:WORLD…]' — "
                        "before optimizer step STEP resize the data "
                        "world to WORLD (implies --elastic) "
                        "[PCNN_ELASTIC_SCHEDULE]")
    p.add_argument("--elastic-scaling", default=None,
                   choices=["global", "per-device"],
                   help="batch/LR response to a resize: global keeps the "
                        "global batch + LR fixed (parity mode), "
                        "per-device keeps the per-device batch and "
                        "scales global batch + LR with the world "
                        "(throughput mode) [PCNN_ELASTIC_SCALING]")
    p.add_argument("--elastic-min-world", type=int, default=None,
                   metavar="N",
                   help="never shrink the data world below N devices; "
                        "deeper chaos losses are clamped and journaled "
                        "[PCNN_ELASTIC_MIN_WORLD]")
    p.add_argument("--async-mode", default=None,
                   choices=["off", "stale", "easgd"],
                   help="straggler-tolerant async data parallelism "
                        "(train/async_dp.py): stale = bounded-staleness "
                        "gradients with a hard barrier only at the bound, "
                        "easgd = independent local SGD with a periodic "
                        "elastic ρ-pull toward a bucket-sharded center; "
                        "off / unset = the bulk-synchronous ring. Async "
                        "modes trade bitwise sync parity for a bounded "
                        "loss delta [PCNN_ASYNC_MODE]")
    p.add_argument("--staleness-bound", type=int, default=None, metavar="S",
                   help="max optimizer-step age of the params a gradient "
                        "may be computed against (--async-mode stale; "
                        "0 = bit-exact with the sync ring) "
                        "[PCNN_ASYNC_STALENESS]")
    p.add_argument("--easgd-period", type=int, default=None, metavar="N",
                   help="local SGD steps between elastic-averaging rounds "
                        "(--async-mode easgd) [PCNN_ASYNC_EASGD_PERIOD]")
    p.add_argument("--easgd-rho", type=float, default=None, metavar="RHO",
                   help="elastic-averaging pull strength in (0, 1]: worker "
                        "and center each move ρ toward the other per round "
                        "(--async-mode easgd) [PCNN_ASYNC_EASGD_RHO]")
    p.add_argument("--metrics", default=None, metavar="PATH",
                   help="append JSONL metrics records to PATH")
    _add_obs_flags(p)
    p.add_argument("--profile", action="store_true",
                   help="lenet_ref: print the per-phase table (paper "
                        "Tables 4-8 shape); zoo models: write a "
                        "jax.profiler trace of 3 steady-state train steps "
                        "to zoo_xla_trace/ under --checkpoint-dir (or cwd)")
    return p


def _add_obs_flags(p: argparse.ArgumentParser) -> None:
    """The shared observability flag surface (train, zoo, serve, loadgen).

    Defaults keep observability fully OFF (the zero-cost no-op bundle);
    PCNN_OBS_* env sets the base and these flags override field-by-field
    (the comm-config layering)."""
    p.add_argument("--trace", action="store_true",
                   help="record host-side spans and the event journal; "
                        "writes a Perfetto-loadable Chrome trace JSON and "
                        "a JSONL journal under --trace-dir on exit "
                        "[PCNN_OBS_TRACE]")
    p.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="artifact directory for the trace + journal "
                        "(implies --trace) [PCNN_OBS_DIR]")
    p.add_argument("--metrics-json", default=None, metavar="PATH",
                   help="write the metrics-registry JSON snapshot to PATH "
                        "on exit (works without --trace: metrics-only "
                        "mode) [PCNN_OBS_METRICS_JSON]")


def _obs_config_from_args(args: argparse.Namespace):
    """Optional[ObsConfig]: env first, flags override field-by-field;
    everything unset → None (observability off, Config.obs default)."""
    obs_cfg = ObsConfig.from_env()
    if args.trace or args.trace_dir or args.metrics_json:
        base = obs_cfg if obs_cfg is not None else ObsConfig(
            trace=bool(args.trace or args.trace_dir)
        )
        obs_cfg = dataclasses.replace(
            base,
            trace=base.trace or bool(args.trace or args.trace_dir),
            dir=args.trace_dir or base.dir,
            metrics_json=args.metrics_json or base.metrics_json,
        )
    return obs_cfg


def config_from_args(args: argparse.Namespace) -> Config:
    data = DataConfig(
        loader=args.loader,
        synthetic_train_count=args.synthetic_train_count,
        synthetic_test_count=args.synthetic_test_count,
    )
    if args.data_dir:
        data = DataConfig(
            train_images=os.path.join(args.data_dir, "train-images.idx3-ubyte"),
            train_labels=os.path.join(args.data_dir, "train-labels.idx1-ubyte"),
            test_images=os.path.join(args.data_dir, "t10k-images.idx3-ubyte"),
            test_labels=os.path.join(args.data_dir, "t10k-labels.idx1-ubyte"),
            loader=args.loader,
            synthetic_train_count=args.synthetic_train_count,
            synthetic_test_count=args.synthetic_test_count,
        )
    train = TrainConfig(
        dt=args.dt,
        threshold=args.threshold,
        epochs=args.epochs,
        batch_size=args.batch_size if args.batch_size is not None else 1,
        seed=args.seed,
        shuffle=args.shuffle,
        prefetch=args.prefetch,
        dtype=args.dtype,
        ops=args.ops,
    )
    # Either flag opts into mesh training; data=None means "all devices
    # not claimed by model" (resolved at mesh build, after the platform
    # override — no jax import may happen here). A bare `--mesh-model 1`
    # is the single-device default and does not activate the mesh.
    mesh = MeshConfig(data=args.mesh_data, model=args.mesh_model or 1)
    resilience = ResilienceConfig(
        policy=args.sentinel,
        max_rollbacks=args.max_rollbacks,
        lr_backoff=args.lr_backoff,
        ring_size=args.keep_checkpoints,
        check_every_steps=args.sentinel_every,
        pallas_fallback=not args.no_pallas_fallback,
    )
    # Env first (PCNN_COMM_*), explicit flags override field-by-field;
    # all-defaults → comm=None, the historical implicit-collective path.
    comm = CommConfig.from_env()
    if (args.comm_impl is not None or args.comm_bucket_mb is not None
            or args.comm_wire_dtype is not None
            or args.comm_hosts is not None):
        base = comm or CommConfig()
        comm = dataclasses.replace(
            base,
            impl=args.comm_impl or base.impl,
            bucket_bytes=(int(args.comm_bucket_mb * 1024 * 1024)
                          if args.comm_bucket_mb is not None
                          else base.bucket_bytes),
            wire_dtype=args.comm_wire_dtype or base.wire_dtype,
            hosts=(args.comm_hosts if args.comm_hosts is not None
                   else base.hosts),
        )
    # Same env-then-flags layering for the fused step. --act-dtype only
    # REFINES an enabled fused path (acceptance: nothing but
    # --fused-step / PCNN_FUSED_STEP changes the default behavior).
    fused = FusedStepConfig.from_env()
    if args.fused_step:
        fused = fused or FusedStepConfig()
    if args.act_dtype is not None:
        if fused is None:
            raise SystemExit(
                "--act-dtype refines the fused step; enable it with "
                "--fused-step (or PCNN_FUSED_STEP=1) first"
            )
        fused = dataclasses.replace(fused, act_dtype=args.act_dtype)
    # Same layering for the pipeline: PCNN_PIPELINE_* env sets the base,
    # any --pipeline-* flag overrides field-by-field (and opts in).
    pipeline = PipelineConfig.from_env()
    if (args.pipeline_stages is not None
            or args.pipeline_split is not None
            or args.pipeline_wire_dtype is not None
            or args.pipeline_act_dtype is not None):
        base = pipeline or PipelineConfig()
        pipeline = dataclasses.replace(
            base,
            stages=(args.pipeline_stages
                    if args.pipeline_stages is not None else base.stages),
            split=(args.pipeline_split
                   if args.pipeline_split is not None else base.split),
            wire_dtype=args.pipeline_wire_dtype or base.wire_dtype,
            act_dtype=args.pipeline_act_dtype or base.act_dtype,
        )
    # Same layering for the elastic runtime: PCNN_ELASTIC* env sets the
    # base, any --elastic* flag overrides field-by-field (and opts in).
    elastic = ElasticConfig.from_env()
    if (args.elastic or args.elastic_schedule is not None
            or args.elastic_scaling is not None
            or args.elastic_min_world is not None):
        base = elastic or ElasticConfig()
        elastic = dataclasses.replace(
            base,
            enabled=True,
            schedule=(args.elastic_schedule
                      if args.elastic_schedule is not None
                      else base.schedule),
            scaling=args.elastic_scaling or base.scaling,
            min_world=(args.elastic_min_world
                       if args.elastic_min_world is not None
                       else base.min_world),
        )
    # And for the async data-parallel modes: PCNN_ASYNC_* env sets the
    # base, any --async*/--staleness*/--easgd* flag overrides (and opts
    # in).  --async-mode off explicitly pins the sync ring even when env
    # vars are set.
    async_dp = AsyncConfig.from_env()
    if (args.async_mode is not None
            or args.staleness_bound is not None
            or args.easgd_period is not None
            or args.easgd_rho is not None):
        base = async_dp or AsyncConfig()
        async_dp = dataclasses.replace(
            base,
            mode=args.async_mode or base.mode,
            staleness_bound=(args.staleness_bound
                             if args.staleness_bound is not None
                             else base.staleness_bound),
            easgd_period=(args.easgd_period
                          if args.easgd_period is not None
                          else base.easgd_period),
            easgd_rho=(args.easgd_rho
                       if args.easgd_rho is not None
                       else base.easgd_rho),
        )
    # --plan / PCNN_PLAN: a serialized ExecutionPlan (written by `tune
    # --report` or `plan show --save`) fills every parallelism knob the
    # env and flags left unset — the same precedence slot as the
    # autotuner's chosen plan (flag > env > plan > default), and knobs it
    # fills are provenance-labeled "autotune" by plan.build_plan.
    args._autotune_filled = set()
    plan_path = getattr(args, "plan", None) or plan_path_from_env()
    if plan_path:
        from parallel_cnn_tpu import plan as plan_lib

        try:
            eplan = plan_lib.load_plan(plan_path)
        except plan_lib.PlanError as exc:
            raise SystemExit(f"--plan: {exc}")
        if comm is None and eplan.comm_impl is not None:
            comm = eplan.comm_config()
            args._autotune_filled |= {
                "comm_impl", "bucket_bytes", "wire_dtype", "overlap",
                "hosts",
            }
        if fused is None and eplan.fused:
            fused = eplan.fused_config()
            args._autotune_filled |= {
                "fused", "fused_update", "fused_tail", "act_dtype", "zero",
            }
        if pipeline is None and (ppc := eplan.pipeline_config()) is not None:
            pipeline = ppc
            args._autotune_filled |= {
                "pipelined", "stages", "split", "pipe_wire_dtype",
                "pipe_act_dtype",
            }
        if args.accum_steps is None and eplan.accum > 1:
            args.accum_steps = eplan.accum
            args._autotune_filled.add("accum")
        if args.mesh_data is None and eplan.data is not None \
                and not (eplan.pipelined or eplan.stages > 1
                         or eplan.comm_impl == "hierarchical"):
            args.mesh_data = eplan.data
            mesh = dataclasses.replace(mesh, data=eplan.data)
            args._autotune_filled.add("data")
        if (args.mesh_model or 1) == 1 and eplan.model > 1:
            args.mesh_model = eplan.model
            mesh = dataclasses.replace(mesh, model=eplan.model)
            args._autotune_filled.add("model")
    # --autotune / PCNN_AUTOTUNE*: env sets the base, flags override —
    # then the report's chosen plan becomes the LOWEST layer: it fills
    # every parallelism subsystem (comm / fused / pipeline /
    # --accum-steps) the env and flags left untouched, so the tuner
    # proposes and explicit knobs always win (plan < env < flags).
    autotune = AutotuneConfig.from_env()
    if args.autotune or args.autotune_report is not None:
        base = autotune or AutotuneConfig()
        autotune = dataclasses.replace(
            base,
            enabled=True,
            report=args.autotune_report or base.report,
        )
    if autotune is not None and autotune.enabled:
        # analysis.autotune is import-light (no jax at module scope), so
        # this stays safe before the backend bootstrap.
        from parallel_cnn_tpu.analysis import autotune as autotune_lib

        try:
            plan, section = autotune_lib.load_chosen_plan(autotune.report)
        except ValueError as exc:  # NoFeasiblePlan / CostSchemaError
            raise SystemExit(f"--autotune: {exc}")
        n_host = int(section.get("n_host", 1) or 1)
        plan_comm, plan_fused, plan_pipe, plan_accum = \
            autotune_lib.plan_to_configs(plan, n_host=n_host)
        if comm is None and plan_comm is not None:
            comm = plan_comm
            args._autotune_filled |= {
                "comm_impl", "bucket_bytes", "wire_dtype", "overlap",
                "hosts",
            }
        if fused is None and plan_fused is not None:
            fused = plan_fused
            args._autotune_filled |= {
                "fused", "fused_update", "fused_tail", "act_dtype", "zero",
            }
        if pipeline is None and plan_pipe is not None:
            pipeline = plan_pipe
            args._autotune_filled |= {
                "pipelined", "stages", "split", "pipe_wire_dtype",
                "pipe_act_dtype",
            }
        if args.accum_steps is None:
            args.accum_steps = plan_accum
            if plan_accum and plan_accum > 1:
                args._autotune_filled.add("accum")
        # The (n_dev, n_host) shape the tuner scored is part of the plan,
        # so the mesh is filled like any other unset knob: a flat
        # single-stage plan activates pure DP over the scored device
        # count. Pipeline and hierarchical plans build their own meshes
        # in the zoo driver (which reads args.mesh_data), and the lenet
        # reference path has no mesh to activate.
        if (args.mesh_data is None and (args.mesh_model or 1) == 1
                and args.model != "lenet_ref"
                and (pipeline is None or pipeline.stages == 1)
                and (comm is None or comm.impl != "hierarchical")):
            plan_dev = int(section.get("n_dev", 0) or 0)
            if plan_dev > 1:
                args.mesh_data = plan_dev
                mesh = dataclasses.replace(mesh, data=plan_dev)
                args._autotune_filled.add("data")
    return Config(data=data, train=train, mesh=mesh,
                  resilience=resilience, comm=comm, fused=fused,
                  obs=_obs_config_from_args(args), elastic=elastic,
                  async_dp=async_dp, pipeline=pipeline,
                  autotune=autotune, model=args.model)


def build_serve_parser(cmd: str) -> argparse.ArgumentParser:
    """Shared flag surface for the `serve` and `loadgen` subcommands.

    Defaults come from ServeConfig.from_env() (the PCNN_SERVE_* table in
    the README), flags override field-by-field — same env-then-flags
    layering as the comm config."""
    sc = ServeConfig.from_env()
    p = argparse.ArgumentParser(
        prog=f"parallel_cnn_tpu {cmd}",
        description=(
            "inference serving (serve/): checkpoint → AOT-compiled, "
            "shape-bucketed, dynamically batched predict"
            if cmd == "serve"
            else "drive the serving stack with seeded traffic and report "
                 "latency percentiles / shed rate"
        ),
    )
    p.add_argument("--model", default=sc.model,
                   choices=["lenet_ref", "cifar_cnn", "resnet18", "resnet34",
                            "resnet50", "vgg16"],
                   help="registry name (serve/registry.py); must match the "
                        "checkpoint's model [PCNN_SERVE_MODEL]")
    p.add_argument("--checkpoint", default=sc.checkpoint,
                   help="restore params (+ BN stats) from this .npz; both "
                        "lenet params-only and zoo full-state checkpoints "
                        "load (optimizer state ignored) "
                        "[PCNN_SERVE_CHECKPOINT]")
    p.add_argument("--conv-backend", default=sc.conv_backend,
                   choices=["xla", "pallas"],
                   help="resnet/vgg only: conv kernel library; pallas takes "
                        "the fused eval epilogues [PCNN_SERVE_CONV_BACKEND]")
    p.add_argument("--max-batch", type=int, default=sc.max_batch,
                   help="top shape bucket (power of two) "
                        "[PCNN_SERVE_MAX_BATCH]")
    p.add_argument("--max-wait-ms", type=float, default=sc.max_wait_ms,
                   help="batch coalescing window [PCNN_SERVE_MAX_WAIT_MS]")
    p.add_argument("--queue-depth", type=int, default=sc.queue_depth,
                   help="bounded request queue; full → typed Overloaded "
                        "shed [PCNN_SERVE_QUEUE_DEPTH]")
    p.add_argument("--replicas", type=int, default=sc.n_replicas,
                   help="engine replicas pinned round-robin across local "
                        "devices [PCNN_SERVE_REPLICAS]")
    p.add_argument("--deadline-ms", type=float, default=sc.deadline_ms,
                   help="per-request deadline budget (0 = none) "
                        "[PCNN_SERVE_DEADLINE_MS]")
    p.add_argument("--no-precompile", action="store_true",
                   help="compile buckets lazily on first use instead of at "
                        "startup [PCNN_SERVE_PRECOMPILE=0]")
    p.add_argument("--admission", action="store_true",
                   help="SLO admission control in front of the queue: "
                        "EWMA reject-early shedding + the graceful-"
                        "degradation ladder (serve/admission.py) "
                        "[PCNN_SERVE_ADMISSION]")
    p.add_argument("--slo-ms", type=float, default=sc.slo_ms,
                   help="completion-time objective: admission budget for "
                        "deadline-less requests, autoscaler p99 target, "
                        "default scenario p99 gate [PCNN_SERVE_SLO_MS]")
    p.add_argument("--autoscale", action="store_true",
                   help="replica autoscaler: grow/drain the pool between "
                        "--replicas and --max-replicas from windowed "
                        "telemetry (serve/autoscaler.py) "
                        "[PCNN_SERVE_AUTOSCALE]")
    p.add_argument("--max-replicas", type=int, default=sc.max_replicas,
                   help="autoscaler ceiling (0 = --replicas: no growth) "
                        "[PCNN_SERVE_MAX_REPLICAS]")
    p.add_argument("--window-s", type=float, default=sc.window_s,
                   help="decay time constant of the windowed telemetry "
                        "the autoscaler reads [PCNN_SERVE_WINDOW_S]")
    p.add_argument("--scenario", default=None,
                   choices=["diurnal", "flash-crowd", "slow-client",
                            "chaos-kill", "chaos-slow", "net-steady",
                            "net-slow-loris", "net-kill-endpoint",
                            "net-hot-swap-diurnal"],
                   help="drive a seeded SLO-gated traffic scenario "
                        "(serve/scenarios.py) instead of plain loadgen; "
                        "exit code reflects the p99/shed/conservation "
                        "gates (chaos-* scenarios need --chaos; net-* "
                        "scenarios need --listen and judge the wire tier "
                        "too)")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="serving fault injection: kill-replica@SEQ kills "
                        "the replica holding dispatch batch SEQ, "
                        "slow-replica@SEQ:MS stalls it MS ms, "
                        "kill-endpoint@SEQ kills the network endpoint at "
                        "wire request SEQ, slow-loris@SEQ:MS stalls a "
                        "client mid-request for MS ms "
                        "(resilience/chaos.py)")
    nc = NetConfig.from_env()
    g = p.add_argument_group(
        "network front door (serve/net.py; PCNN_SERVE_* in docs/api.md)")
    g.add_argument("--listen", action="store_true", default=nc.listen,
                   help="serve over a real TCP socket (NDJSON protocol) "
                        "instead of in-process submit; traffic/scenarios "
                        "are driven through the socket transport "
                        "[PCNN_SERVE_LISTEN]")
    g.add_argument("--listen-host", default=nc.host,
                   help="bind address for --listen [PCNN_SERVE_HOST]")
    g.add_argument("--listen-port", type=int, default=nc.port,
                   help="bind port for --listen; 0 = ephemeral (the "
                        "supervisor respawns on whatever was bound) "
                        "[PCNN_SERVE_PORT]")
    g.add_argument("--conn-deadline-ms", type=float,
                   default=nc.conn_deadline_ms,
                   help="per-connection read/write deadline: a socket "
                        "stalling mid-request past it is reaped as "
                        "expired (slow-loris defense); also the budget "
                        "of deadline-less wire requests "
                        "[PCNN_SERVE_CONN_DEADLINE_MS]")
    g.add_argument("--aot-cache-dir", default=nc.aot_cache_dir,
                   help="persistent on-disk AOT-executable cache: warm "
                        "cold-starts skip every bucket compile; torn or "
                        "fingerprint-mismatched entries recompile with a "
                        "typed AotCacheWarning "
                        "[PCNN_SERVE_AOT_CACHE_DIR]")
    g.add_argument("--supervise", action="store_true", default=nc.supervise,
                   help="respawn a killed endpoint on the same port with "
                        "bounded exponential backoff "
                        "(serve/supervisor.py) [PCNN_SERVE_SUPERVISE]")
    g.add_argument("--swap-checkpoint", default=None, metavar="PATH",
                   help="net-hot-swap-diurnal: checkpoint to hot-swap in "
                        "mid-peak (default: fresh seed+1 init)")
    p.add_argument("--requests", type=int,
                   default=64 if cmd == "serve" else 512,
                   help="traffic volume to drive through the stack")
    p.add_argument("--pattern", default="closed",
                   choices=["closed", "open"],
                   help="arrival pattern (serve/loadgen.py): closed-loop "
                        "concurrency or open-loop Poisson")
    p.add_argument("--concurrency", type=int, default=8,
                   help="closed loop: synchronous client count")
    p.add_argument("--rate", type=float, default=500.0,
                   help="open loop: offered Poisson rate, req/s")
    p.add_argument("--seed", type=int, default=0,
                   help="payload + arrival-process seed (replayable)")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="write the report/telemetry snapshot as JSON")
    _add_obs_flags(p)
    return p


def _serve_config_from_args(args: argparse.Namespace) -> ServeConfig:
    env = ServeConfig.from_env()
    return ServeConfig(
        model=args.model,
        checkpoint=args.checkpoint,
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        queue_depth=args.queue_depth,
        n_replicas=args.replicas,
        deadline_ms=args.deadline_ms,
        conv_backend=args.conv_backend,
        precompile=not args.no_precompile,
        admission=args.admission or env.admission,
        slo_ms=args.slo_ms,
        autoscale=args.autoscale or env.autoscale,
        max_replicas=args.max_replicas,
        window_s=args.window_s,
    )


def _net_config_from_args(args: argparse.Namespace) -> NetConfig:
    env = NetConfig.from_env()
    return NetConfig(
        listen=args.listen or env.listen,
        host=args.listen_host,
        port=args.listen_port,
        conn_deadline_ms=args.conn_deadline_ms,
        aot_cache_dir=args.aot_cache_dir,
        supervise=args.supervise or env.supervise,
        respawn_attempts=env.respawn_attempts,
        respawn_base_delay_s=env.respawn_base_delay_s,
        respawn_max_delay_s=env.respawn_max_delay_s,
    )


def _run_serve(cmd: str, argv: List[str]) -> int:
    """`serve` and `loadgen` subcommands.

    `serve` is the operator's view: restore the checkpoint, AOT-compile
    the bucket ladder (printing the compile-cache table), prove the
    padding/parity contract on one padded bucket, drive a short smoke of
    traffic, and print the telemetry snapshot. `loadgen` is the
    benchmarker's view: the same stack under a chosen arrival pattern,
    reporting client-side p50/p90/p99 and shed rate (optionally as JSON).
    By default the surface is in-process (batcher.submit); `--listen`
    puts the network front door (serve/net.py: NDJSON over TCP,
    per-connection deadlines, wire-tier conservation) in front of it
    and drives the same traffic through real sockets — optionally
    supervised (`--supervise`: crash-fast respawn on a stable port) and
    with the persistent AOT-executable cache (`--aot-cache-dir`)
    warming cold starts.
    """
    args = build_serve_parser(cmd).parse_args(argv)
    cfg = _serve_config_from_args(args)
    ncfg = _net_config_from_args(args)

    import jax

    if os.environ.get("PCNN_JAX_PLATFORMS"):  # graftcheck: disable=env-outside-config -- platform override must reach jax.config before backend init; tests/conftest.py documents why the env var alone is insufficient
        jax.config.update("jax_platforms", os.environ["PCNN_JAX_PLATFORMS"])  # graftcheck: disable=env-outside-config -- platform override must reach jax.config before backend init; tests/conftest.py documents why the env var alone is insufficient
    import json as json_mod
    import time

    import numpy as np

    from parallel_cnn_tpu.serve import (
        AutoScaler,
        get,
        loadgen,
        scenarios,
        serve_stack,
    )

    handle = get(cfg.model, conv_backend=cfg.conv_backend)
    obs_bundle = obs_lib.from_config(_obs_config_from_args(args), run=cmd)
    chaos = None
    if args.chaos:
        from parallel_cnn_tpu.resilience.chaos import ChaosMonkey

        chaos = ChaosMonkey.from_spec(args.chaos)
    t0 = time.perf_counter()
    pool, batcher = serve_stack(handle, cfg, obs=obs_bundle, chaos=chaos,
                                cache_dir=ncfg.aot_cache_dir)
    startup = time.perf_counter() - t0
    if obs_bundle.enabled:
        # Exposition parity: the ServeStats counters feed the registry's
        # Prometheus/JSON snapshots without changing their semantics.
        batcher.stats.attach_registry(obs_bundle.registry)
        if batcher.admission is not None:
            batcher.admission.attach_registry(obs_bundle.registry)
    src = cfg.checkpoint or "fresh init (no --checkpoint)"
    print(f"[serve] model={cfg.model} params from {src}")
    print(f"[serve] replicas={cfg.n_replicas} on "
          f"{[str(e.device) for e in pool.engines]}")
    if cfg.admission:
        print(f"[serve] admission control on (SLO {cfg.slo_ms:g} ms)")
    scaler = None
    if cfg.autoscale:
        scaler = AutoScaler(
            pool, batcher,
            min_replicas=1,
            max_replicas=cfg.effective_max_replicas,
            slo_ms=cfg.slo_ms,
            obs=obs_bundle,
        )
        if obs_bundle.enabled:
            scaler.attach_registry(obs_bundle.registry)
        scaler.start()
        print(f"[serve] autoscaler on "
              f"(1..{cfg.effective_max_replicas} replicas, "
              f"p99 target {cfg.slo_ms:g} ms)")
    if cfg.precompile:
        buckets = pool.engines[0].stats.compile_seconds
        table = ", ".join(f"b{b}: {s * 1e3:.0f} ms"
                          for b, s in sorted(buckets.items()))
        print(f"[serve] AOT bucket ladder compiled in {startup:.2f}s "
              f"({table})")
    if ncfg.aot_cache_dir:
        hits = sum(e.stats.aot_cache_hits for e in pool.engines)
        misses = sum(e.stats.aot_cache_misses for e in pool.engines)
        corrupt = sum(e.stats.aot_cache_corrupt for e in pool.engines)
        print(f"[serve] AOT disk cache {ncfg.aot_cache_dir}: "
              f"{hits} hits, {misses} misses, {corrupt} corrupt "
              f"(warm start = zero compiles)")

    with batcher:
        if cmd == "serve":
            # Padding parity probe (the dryrun leg's cheap twin): padded
            # bucket prediction must be bit-identical to the same-bucket
            # jit forward.
            import jax.numpy as jnp

            e0 = pool.engines[0]
            b = min(4, cfg.max_batch)
            n = max(b - 1, 1)
            xs = loadgen.make_samples(n, handle.in_shape, seed=args.seed)
            got = e0.predict(xs)
            pad = np.zeros((b - n, *handle.in_shape), np.float32)
            ref = np.asarray(jax.jit(
                lambda v: handle.forward(e0._params, e0._state, v)
            )(jnp.concatenate([jnp.asarray(xs), jnp.asarray(pad)])))[:n]
            parity = "bit-identical" if np.array_equal(got, ref) else (
                f"MISMATCH (max |Δ| {float(np.max(np.abs(got - ref))):.2e})"
            )
            print(f"[serve] padded-bucket parity (n={n}→b{b}): {parity}")

        rc = 0
        sup = None
        endpoint = None
        wire = None
        if args.scenario and args.scenario.startswith("net-") \
                and not ncfg.listen:
            print(f"[{cmd}] scenario {args.scenario} needs --listen "
                  f"(it judges the wire tier)")
            return 2
        if ncfg.listen:
            from parallel_cnn_tpu.resilience.retry import RetryPolicy
            from parallel_cnn_tpu.serve.net import NetServer
            from parallel_cnn_tpu.serve.supervisor import Supervisor
            from parallel_cnn_tpu.serve.telemetry import WireStats

            wire = WireStats()
            if obs_bundle.enabled:
                wire.attach_registry(obs_bundle.registry)
            # A kill-endpoint monkey arms the SERVER (first incarnation
            # only — a respawn must not replay the death); a slow-loris
            # monkey arms the CLIENT side of the socket transport.
            server_chaos = (
                chaos if chaos is not None
                and chaos.kill_endpoint_seq is not None else None
            )
            client_chaos = (
                chaos if chaos is not None
                and chaos.slow_loris is not None else None
            )
            armed = [server_chaos]

            def _factory(port: int, seq_start: int):
                m = armed.pop(0) if armed else None
                return NetServer(
                    batcher, host=ncfg.host, port=port,
                    conn_deadline_ms=ncfg.conn_deadline_ms, wire=wire,
                    chaos=m, obs=obs_bundle, seq_start=seq_start,
                ).start()

            if ncfg.supervise:
                sup = Supervisor(
                    _factory,
                    policy=RetryPolicy(
                        attempts=ncfg.respawn_attempts,
                        base_delay=ncfg.respawn_base_delay_s,
                        max_delay=ncfg.respawn_max_delay_s,
                        seed=args.seed,
                    ),
                    obs=obs_bundle, port=ncfg.port,
                ).start()
                endpoint = sup.server
            else:
                endpoint = _factory(ncfg.port, 0)
            print(f"[{cmd}] listening on "
                  f"{endpoint.host}:{endpoint.port} "
                  f"(conn deadline {ncfg.conn_deadline_ms:g} ms"
                  + (", supervised" if sup is not None else "") + ")")
        if args.scenario and args.scenario.startswith("net-"):
            swap_params = swap_state = None
            if args.scenario == "net-hot-swap-diurnal":
                from parallel_cnn_tpu.serve.engine import load_or_init

                swap_params, swap_state = load_or_init(
                    handle, args.swap_checkpoint, seed=args.seed + 1,
                )
            report = scenarios.run_net(
                args.scenario, batcher, wire=wire,
                supervisor=sup, server=endpoint, chaos=client_chaos,
                swap_params=swap_params, swap_state=swap_state,
                obs=obs_bundle, seed=args.seed,
            )
            gates = report.gates()
            verdict = "PASS" if report.passed else "FAIL"
            p99 = report.p99_ms
            print(f"[{cmd}] scenario {report.name}: "
                  f"{report.completed}/{report.requests} ok, "
                  f"shed rate {report.shed_rate:.3f}, "
                  f"p99 {p99:.1f} ms" if p99 is not None else
                  f"[{cmd}] scenario {report.name}: no completions")
            print(f"[{cmd}] gates {verdict}: " + ", ".join(
                f"{k}={'ok' if v else 'TRIPPED'}"
                for k, v in gates.items()
            ))
            rc = 0 if report.passed else 1
        elif args.scenario:
            report = scenarios.run(
                args.scenario, batcher,
                seed=args.seed,
                deadline_ms=args.deadline_ms or None,
            )
            gates = report.gates()
            verdict = "PASS" if report.passed else "FAIL"
            p99 = report.p99_ms
            print(f"[{cmd}] scenario {report.name}: "
                  f"{report.completed}/{report.requests} ok, "
                  f"shed rate {report.shed_rate:.3f}, "
                  f"p99 {p99:.1f} ms" if p99 is not None else
                  f"[{cmd}] scenario {report.name}: no completions")
            print(f"[{cmd}] gates {verdict}: " + ", ".join(
                f"{k}={'ok' if v else 'TRIPPED'}"
                for k, v in gates.items()
            ))
            rc = 0 if report.passed else 1
        elif ncfg.listen:
            report = loadgen.run_closed_loop_net(
                endpoint.address,
                loadgen.make_samples(
                    min(args.requests, 64), handle.in_shape,
                    seed=args.seed,
                ),
                n_requests=args.requests,
                concurrency=args.concurrency,
                deadline_ms=args.deadline_ms or None,
                seed=args.seed,
                chaos=client_chaos,
            )
            print(f"[{cmd}] closed-net-loop: "
                  f"{report.completed}/{report.requests} ok, "
                  f"{report.throughput:.1f} req/s over the wire, "
                  f"shed rate {report.shed_rate:.3f}")
            lat = report.latency.summary(scale=1e3)
            if lat.get("count"):
                print(f"[{cmd}] latency p50 {lat['p50']:.2f} ms, "
                      f"p90 {lat['p90']:.2f} ms, p99 {lat['p99']:.2f} ms")
        else:
            report = loadgen.run(
                batcher,
                pattern=args.pattern,
                n_requests=args.requests,
                concurrency=args.concurrency,
                rate=args.rate,
                deadline_ms=args.deadline_ms or None,
                seed=args.seed,
            )
            print(f"[{cmd}] {args.pattern}-loop: "
                  f"{report.completed}/{report.requests} ok, "
                  f"{report.throughput:.1f} req/s, "
                  f"shed rate {report.shed_rate:.3f}")
            lat = report.latency.summary(scale=1e3)
            if lat.get("count"):
                print(f"[{cmd}] latency p50 {lat['p50']:.2f} ms, "
                      f"p90 {lat['p90']:.2f} ms, p99 {lat['p99']:.2f} ms")
        if ncfg.listen:
            (sup if sup is not None else endpoint).close()
            w = wire.snapshot()
            print(f"[{cmd}] wire: {w['submitted']} submitted = "
                  f"{w['completed']} completed + {w['shed']} shed + "
                  f"{w['expired']} expired + {w['failed']} failed "
                  f"({'balanced' if wire.balanced() else 'IMBALANCED'}; "
                  f"{w['reaped']} reaped, "
                  f"{w['endpoint_deaths']} endpoint deaths"
                  + (f", {sup.respawns} respawns" if sup is not None
                     else "") + ")")
        if scaler is not None:
            scaler.close()
            snap = scaler.snapshot()
            print(f"[{cmd}] autoscaler: {snap['scale_ups']} up, "
                  f"{snap['scale_downs']} down, "
                  f"{snap['routable']} replicas routable")
        print(batcher.stats.render())
        if args.json:
            out = {"config": dataclasses.asdict(cfg),
                   "report": report.to_dict(),
                   "telemetry": batcher.stats.snapshot(),
                   "window": batcher.stats.window_snapshot()}
            if batcher.admission is not None:
                out["admission"] = batcher.admission.snapshot()
            if scaler is not None:
                out["autoscaler"] = scaler.snapshot()
            if wire is not None:
                out["wire"] = wire.snapshot()
            with open(args.json, "w") as f:
                json_mod.dump(out, f, indent=2)
            print(f"[{cmd}] report written to {args.json}")
    for kind, path in obs_bundle.finish().items():
        print(f"[{cmd}] {kind} written to {path}")
    return rc


def _run_check(argv: List[str]) -> int:
    """`python -m parallel_cnn_tpu check` — graftcheck static analysis.

    A host-side lint pass: it never needs (or touches) an accelerator,
    so CPU is forced unconditionally, with 8 virtual devices so the
    mesh-shaped jaxpr analyzers can trace the real collective schedules.
    Both knobs must land before jax initializes a backend — hence the
    env write here, first thing, mirroring tests/conftest.py (the
    ambient plugin snapshots XLA_FLAGS at import)."""
    flags = os.environ.get("XLA_FLAGS", "")  # graftcheck: disable=env-outside-config -- backend bootstrap, must precede jax import; not a tunable knob
    if "xla_force_host_platform_device_count" not in flags:
        # graftcheck: disable=env-outside-config -- backend bootstrap, must precede jax import; not a tunable knob
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already initialized (embedded call): analyze as-is

    # PCNN_CHECK_COST=1 turns on the cost/sharding families for every
    # check invocation — the CI spelling of `check --cost` (docs/api.md).
    # graftcheck: disable=env-outside-config -- check-dispatch knob: must act before checker argparse, config.py is not imported on this path
    if os.environ.get("PCNN_CHECK_COST", "").lower() in ("1", "true") \
            and "--cost" not in argv:
        argv = ["--cost"] + argv

    from parallel_cnn_tpu.analysis import checker

    return checker.main(argv)


def _run_tune(argv: List[str]) -> int:
    """`python -m parallel_cnn_tpu tune` — rank the parallelism-plan
    space against the analytic roofline and write the chosen plan into
    the cost report (docs/autotuning.md).

    Search is pure closed-form arithmetic; jax is needed only to profile
    the model (param/flop/activation tables), so CPU is forced with 8
    virtual devices exactly like `check` — the tuner must run on a
    devbox, not burn accelerator time."""
    flags = os.environ.get("XLA_FLAGS", "")  # graftcheck: disable=env-outside-config -- backend bootstrap, must precede jax import; not a tunable knob
    if "xla_force_host_platform_device_count" not in flags:
        # graftcheck: disable=env-outside-config -- backend bootstrap, must precede jax import; not a tunable knob
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already initialized (embedded call): profile as-is

    from parallel_cnn_tpu.analysis import autotune as autotune_lib
    from parallel_cnn_tpu.analysis import hw_profiles

    at = AutotuneConfig.from_env() or AutotuneConfig()
    p = argparse.ArgumentParser(
        prog="parallel_cnn_tpu tune",
        description="cost-model plan autotuner (analysis/autotune.py)",
    )
    p.add_argument("--model", default="cifar_cnn",
                   choices=["cifar_cnn", "resnet18", "resnet34", "resnet50",
                            "vgg16"],
                   help="zoo model the plan space is profiled for")
    p.add_argument("--global-batch", type=int, default=128, metavar="B",
                   help="global batch size every plan must serve")
    p.add_argument("--devices", type=int, default=None, metavar="N",
                   help="device count the plans are laid out over "
                        "(default: all local devices)")
    p.add_argument("--hosts", type=int, default=1, metavar="H",
                   help="emulated host count (hierarchical plans need "
                        ">= 2; flat rings spanning hosts are charged at "
                        "DCN speed)")
    p.add_argument("--hw", default=at.hw, metavar="NAME",
                   help="hardware profile scored against "
                        f"({', '.join(sorted(hw_profiles.PROFILES))}) "
                        "[PCNN_HW_PROFILE]")
    p.add_argument("--hbm-budget-mb", type=float, default=None, metavar="MB",
                   help="peak-HBM budget per device; default: the "
                        "profile's capacity [PCNN_AUTOTUNE_HBM_BUDGET]")
    p.add_argument("--top-k", type=int, default=at.top_k,
                   help="ranked plans kept in the report "
                        "[PCNN_AUTOTUNE_TOPK]")
    p.add_argument("--report", default=at.report, metavar="PATH",
                   help="cost report the autotune section is merged into; "
                        "default: the shipped analysis/cost_report.json "
                        "[PCNN_AUTOTUNE_REPORT]")
    p.add_argument("--no-prune", action="store_true",
                   help="score every feasible plan (disable the "
                        "admissible compute-lower-bound prune; results "
                        "are identical by construction — debug only)")
    args = p.parse_args(argv)

    from parallel_cnn_tpu.nn import cifar, resnet, vgg

    factories = {
        "cifar_cnn": lambda: cifar.cifar_cnn(),
        "resnet18": lambda: resnet.resnet18(10, cifar_stem=True),
        "resnet34": lambda: resnet.resnet34(10, cifar_stem=True),
        "resnet50": lambda: resnet.resnet50(10, cifar_stem=True),
        "vgg16": lambda: vgg.vgg16(10),
    }
    model = factories[args.model]()
    mp = autotune_lib.profile_module(model, cifar.IN_SHAPE, name=args.model)
    hw = hw_profiles.get_profile(args.hw)
    n_dev = args.devices or jax.local_device_count()
    budget = (int(args.hbm_budget_mb * 1024 * 1024)
              if args.hbm_budget_mb is not None else at.hbm_budget)
    try:
        result = autotune_lib.search(
            mp, hw=hw, global_batch=args.global_batch, n_dev=n_dev,
            n_host=args.hosts, hbm_budget=budget, top_k=args.top_k,
            prune=not args.no_prune,
        )
    except autotune_lib.NoFeasiblePlan as exc:
        print(f"tune: {exc}")
        return 1
    print(autotune_lib.format_table(result))
    written = autotune_lib.write_section(
        args.report, autotune_lib.build_section(result))
    # Embed the chosen plan as a first-class ExecutionPlan document so
    # the report itself is a --plan file — the lossless tune → train
    # artifact hand-off (docs/execution_plan.md).
    import json as json_mod

    from parallel_cnn_tpu import plan as plan_lib

    chosen, section = autotune_lib.load_chosen_plan(written)
    eplan = chosen.to_execution_plan(
        n_host=int(section.get("n_host", 1) or 1),
        n_dev=int(section.get("n_dev", 0) or 0) or None,
    )
    with open(written) as f:
        doc = json_mod.load(f)
    doc["plan"] = eplan.to_json_dict()
    with open(written, "w") as f:
        json_mod.dump(doc, f, sort_keys=True, indent=2)
        f.write("\n")
    print(f"tune: chosen plan written to {written} "
          f"(plan {eplan.fingerprint()}; run with --plan {written})")
    return 0


def _run_plan(argv: List[str]) -> int:
    """`python -m parallel_cnn_tpu plan show|diff` — the resolved
    ExecutionPlan as a first-class object (docs/execution_plan.md).

    `plan show [train flags] [--save PATH]` resolves exactly the plan a
    train run with those flags would execute (flag > env > plan-file >
    default) and prints it one knob per line with per-knob provenance;
    `plan diff A B` prints a field-by-field diff of two plan files.
    Both are pure host-side paths: no jax, no backend, no devices."""
    from parallel_cnn_tpu import plan as plan_lib

    if not argv or argv[0] not in ("show", "diff"):
        print("usage: parallel_cnn_tpu plan show [train flags] "
              "[--save PATH]\n"
              "       parallel_cnn_tpu plan diff PLAN_A PLAN_B")
        return 2
    if argv[0] == "diff":
        if len(argv) != 3:
            print("usage: parallel_cnn_tpu plan diff PLAN_A PLAN_B")
            return 2
        try:
            a = plan_lib.load_plan(argv[1])
            b = plan_lib.load_plan(argv[2])
        except plan_lib.PlanError as exc:
            print(f"plan diff: {exc}")
            return 2
        out = plan_lib.diff_plans(a, b)
        if not out:
            print(f"plans identical ({a.fingerprint()})")
            return 0
        print(out)
        return 1
    p = build_parser()
    p.add_argument("--save", default=None, metavar="PATH",
                   help="also write the resolved plan as a --plan-loadable "
                        "plan.json")
    args = p.parse_args(argv[1:])
    cfg = config_from_args(args)
    plan = plan_lib.build_plan(cfg, args)
    verdict = ""
    try:
        plan.validate()
    except plan_lib.PlanError as exc:
        verdict = f"\nILLEGAL: {exc}"
    if args.save:
        plan_lib.save_plan(args.save, plan)
    print(plan_lib.format_plan(plan, title=f"resolved plan ({cfg.model})")
          + verdict)
    if args.save:
        print(f"plan written to {args.save}")
    return 1 if verdict else 0


def main(argv: Optional[List[str]] = None) -> int:
    import sys

    raw = list(sys.argv[1:] if argv is None else argv)
    # Subcommand dispatch rides in front of the historical flat trainer
    # CLI: `python -m parallel_cnn_tpu serve|loadgen …` routes to the
    # serving stack, anything else keeps the original flag surface
    # unchanged (no retrofit of subparsers onto existing automation).
    if raw and raw[0] in ("serve", "loadgen"):
        return _run_serve(raw[0], raw[1:])
    if raw and raw[0] == "check":
        return _run_check(raw[1:])
    if raw and raw[0] == "tune":
        return _run_tune(raw[1:])
    if raw and raw[0] == "plan":
        return _run_plan(raw[1:])
    args = build_parser().parse_args(raw)
    cfg = config_from_args(args)

    # Surface the data pipeline's INFO-level evidence (e.g. the real-MNIST
    # integrity report) in the driver; library embedders keep their own
    # logging policy and a clean stdout.
    import logging

    logging.getLogger("parallel_cnn_tpu").setLevel(logging.INFO)
    if not logging.getLogger().handlers:
        logging.basicConfig(
            level=logging.INFO, format="%(levelname)s %(name)s: %(message)s"
        )

    import jax

    # Reliable platform override: the ambient plugin snapshots JAX_PLATFORMS
    # before user code (tests/conftest.py documents this), so the env var
    # alone can't force CPU — jax.config.update can.
    if os.environ.get("PCNN_JAX_PLATFORMS"):  # graftcheck: disable=env-outside-config -- platform override must reach jax.config before backend init; tests/conftest.py documents why the env var alone is insufficient
        jax.config.update("jax_platforms", os.environ["PCNN_JAX_PLATFORMS"])  # graftcheck: disable=env-outside-config -- platform override must reach jax.config before backend init; tests/conftest.py documents why the env var alone is insufficient
    import jax.numpy as jnp

    from parallel_cnn_tpu.data import pipeline
    from parallel_cnn_tpu.models import lenet_ref
    from parallel_cnn_tpu.parallel import distributed
    from parallel_cnn_tpu.resilience import ChaosMonkey, CheckpointRing
    from parallel_cnn_tpu.resilience import preempt
    from parallel_cnn_tpu.train import checkpoint, trainer
    from parallel_cnn_tpu.utils.metrics import MetricsLogger, throughput
    from parallel_cnn_tpu.utils import profiling

    distributed.initialize()  # env-configured multi-host; no-op otherwise

    if cfg.model != "lenet_ref":
        if cfg.async_dp is not None and cfg.async_dp.enabled:
            raise SystemExit(
                "--async-mode drives the lenet_ref virtual-clock harness "
                "(train/async_dp.py); zoo models stay bulk-synchronous — "
                "drop --async-mode or use --model lenet_ref"
            )
        return _run_zoo(args, cfg)
    if cfg.elastic is not None and cfg.elastic.enabled:
        # The flat per-sample trainer has no sharded optimizer state to
        # re-lay-out; only the zoo ZeRO-3 step can resize in flight.
        raise SystemExit(
            "--elastic needs the zoo ZeRO-3 trainer: pick a zoo --model "
            "(e.g. cifar_cnn) with --mesh-data, --comm-impl ring and "
            "--fused-step"
        )
    train_ds, test_ds = pipeline.load_train_test(cfg.data)

    chaos = ChaosMonkey.from_spec(args.chaos) if args.chaos else None
    if cfg.async_dp is not None and cfg.async_dp.enabled:
        return _run_async_lenet(args, cfg, train_ds, test_ds, chaos)
    ring = None
    if args.checkpoint_dir:
        ring = CheckpointRing(
            args.checkpoint_dir, keep=cfg.resilience.ring_size
        )

    params = None
    start_epoch = 0
    error_history: List[float] = []
    if args.checkpoint_dir and args.resume:
        path = checkpoint.latest(args.checkpoint_dir)
        if path:
            like = lenet_ref.init(jax.random.key(cfg.train.seed))
            params, state = checkpoint.restore(path, like)
            start_epoch = state.epoch
            error_history = list(state.epoch_errors)
            print(f"resumed from {path} (epoch {start_epoch})")

    metrics = MetricsLogger(path=args.metrics) if args.metrics else None
    obs_bundle = obs_lib.from_config(cfg.obs, run="train")
    remaining = max(cfg.train.epochs - start_epoch, 0)
    run_cfg = cfg.replace(
        train=dataclasses.replace(cfg.train, epochs=remaining)
    )

    def on_epoch(epoch: int, epoch_params, err: float) -> None:
        """Mid-training persistence: fires after every epoch, so a killed
        run resumes from its last finished epoch, not from nothing."""
        error_history.append(err)
        if metrics:
            metrics.record(event="epoch", epoch=epoch, error=err)
        if ring is not None:
            ring.save(
                epoch,
                epoch_params,
                checkpoint.TrainState(
                    epoch=epoch, epoch_errors=list(error_history)
                ),
            )

    # SIGTERM/SIGINT stop training at the next epoch boundary with the
    # checkpoint already flushed (resilience/preempt) — the cloud
    # preemption contract the reference lacks.
    with preempt.PreemptionGuard() as guard:
        result = trainer.learn(
            run_cfg,
            train_ds,
            params=params,
            epoch_offset=start_epoch,
            epoch_callback=on_epoch,
            chaos=chaos,
            ring=ring,
            obs=obs_bundle,
        )

    for kind, path in obs_bundle.finish().items():
        print(f"[obs] {kind} written to {path}")
    if result.preempted or guard.preempted:
        if metrics:
            metrics.record(
                event="preempted",
                epoch=start_epoch + len(result.epoch_errors),
            )
            metrics.close()
        print("preempted: checkpoint flushed; continue with --resume")
        return 0

    rate = trainer.test(result.params, test_ds)
    if metrics:
        n_images = len(train_ds) * max(len(result.epoch_errors), 1)
        metrics.record(
            event="final",
            error_rate=rate,
            seconds=result.seconds,
            images_per_sec=throughput(n_images, result.seconds),
        )
        metrics.close()

    if args.profile:
        bsz = max(cfg.train.batch_size, 256)
        xs = jnp.asarray(train_ds.images[:bsz])
        ys = jnp.asarray(train_ds.labels[:bsz])
        phases = profiling.profile_phases(result.params, xs, ys)
        print(profiling.report(phases, n_images=xs.shape[0]))

    return 0


def _run_async_lenet(args, cfg: Config, train_ds, test_ds, chaos) -> int:
    """Async data-parallel driver branch (--async-mode stale|easgd).

    Runs the deterministic virtual-clock harness (train/async_dp.py):
    N logical workers, each resident on its own shard of the training
    set, real jitted gradients, virtual step durations — so throughput
    and straggler tolerance replay exactly, chaos ``slow-worker@`` and
    all.  One optimizer step consumes every worker's resident microbatch
    once, so ``--epochs`` counts server steps (stale) / per-worker local
    steps (easgd)."""
    import jax
    import jax.numpy as jnp

    from parallel_cnn_tpu.models import lenet_ref
    from parallel_cnn_tpu.resilience.sentinel import Sentinel
    from parallel_cnn_tpu.train import async_dp, trainer

    acfg = cfg.async_dp
    w, b = acfg.workers, cfg.train.batch_size
    if len(train_ds) < w * b:
        raise SystemExit(
            f"async harness wants {w} workers x {b} images, dataset has "
            f"{len(train_ds)}"
        )
    xs = jnp.asarray(train_ds.images[: w * b]).reshape(w, b, 28, 28)
    ys = jnp.asarray(train_ds.labels[: w * b]).reshape(w, b)
    params = lenet_ref.init(jax.random.key(cfg.train.seed))
    obs_bundle = obs_lib.from_config(cfg.obs, run="train_async")

    result = async_dp.run_async(
        params, xs, ys, cfg=acfg, dt=cfg.train.dt,
        max_server_steps=cfg.train.epochs, chaos=chaos,
        sentinel=Sentinel(), obs=obs_bundle,
    )
    for kind, path in obs_bundle.finish().items():
        print(f"[obs] {kind} written to {path}")
    print(
        f"async mode={acfg.mode} steps={result.server_steps} "
        f"microbatches={result.microbatches} "
        f"virtual_ms={result.virtual_ms:.0f} "
        f"max_staleness={result.ledger.max_staleness()} "
        f"stragglers={result.stragglers} dropped={result.dropped} "
        f"easgd_rounds={result.easgd_rounds}"
    )
    rate = trainer.test(result.params, test_ds)
    print(f"async test error rate: {rate:.4f}")
    return 0


def _run_zoo(args: argparse.Namespace, cfg: Config) -> int:
    """Zoo-model driver branch (--model {cifar_cnn,resnet18,34,50,vgg16}).

    Trains on the deterministic synthetic CIFAR-shape stand-in (this
    environment cannot fetch CIFAR/ImageNet — BASELINE.md), with the
    production surface zoo.train provides: per-epoch eval, atomic
    checkpoint/resume of the FULL state, JSONL metrics, GSPMD DP over a
    --mesh-data mesh (plus filter sharding with --mesh-model N>1), and
    --conv-backend pallas for the native kernels.
    """
    from parallel_cnn_tpu import plan as plan_lib
    from parallel_cnn_tpu.data import synthetic
    from parallel_cnn_tpu.nn import cifar, resnet, vgg
    from parallel_cnn_tpu.resilience import ChaosMonkey
    from parallel_cnn_tpu.resilience import preempt
    from parallel_cnn_tpu.train import zoo
    from parallel_cnn_tpu.utils.metrics import MetricsLogger

    factories = {
        "cifar_cnn": lambda: cifar.cifar_cnn(),
        "resnet18": lambda: resnet.resnet18(
            10, cifar_stem=True, conv_backend=args.conv_backend
        ),
        "resnet34": lambda: resnet.resnet34(
            10, cifar_stem=True, conv_backend=args.conv_backend
        ),
        "resnet50": lambda: resnet.resnet50(
            10, cifar_stem=True, conv_backend=args.conv_backend
        ),
        "vgg16": lambda: vgg.vgg16(10, conv_backend=args.conv_backend),
    }
    if cfg.model == "cifar_cnn" and args.conv_backend != "xla":
        raise SystemExit(
            "--conv-backend pallas applies to the resnet/vgg models"
        )
    model = factories[cfg.model]()

    imgs, labels = synthetic.make_image_dataset(
        args.synthetic_train_count, seed=cfg.data.synthetic_seed
    )
    ev_imgs, ev_labels = synthetic.make_image_dataset(
        args.synthetic_test_count, seed=cfg.data.synthetic_seed + 1
    )

    # ONE resolution + legality + mesh-construction site: the three
    # historical mesh branches (flat ring / hierarchical / pipeline) and
    # their ad-hoc knob guards all live in plan.build_plan / validate /
    # make_mesh now (docs/execution_plan.md has the legality matrix).
    try:
        eplan = plan_lib.build_plan(cfg, args).validate()
    except plan_lib.PlanError as exc:
        raise SystemExit(str(exc))
    mesh = eplan.make_mesh()
    model_axis = eplan.model > 1
    if mesh is not None:
        kind = ("pipeline" if eplan.pipelined or eplan.stages > 1
                else "hierarchical" if eplan.comm_impl == "hierarchical"
                else None)
        print(f"mesh: {dict(mesh.shape)}" + (f" ({kind})" if kind else ""))

    metrics = MetricsLogger(path=args.metrics) if args.metrics else None
    # batch-size sentinel: zoo default is minibatch 128; an explicit 1 is
    # a config error (per-sample SGD is the lenet_ref parity mode).
    if args.batch_size is None:
        batch = 128
    elif args.batch_size == 1:
        raise SystemExit("zoo models train minibatch; use --batch-size > 1")
    else:
        batch = args.batch_size
    chaos = ChaosMonkey.from_spec(args.chaos) if args.chaos else None
    obs_bundle = obs_lib.from_config(cfg.obs, run="zoo")
    with preempt.PreemptionGuard() as guard:
        zoo.train(
            model,
            imgs,
            labels,
            in_shape=cifar.IN_SHAPE,
            epochs=args.epochs,
            batch_size=batch,
            lr=args.lr,
            lr_schedule=args.lr_schedule,
            warmup_steps=args.warmup_steps,
            augment=args.augment,
            accum_steps=args.accum_steps or 1,
            mesh=mesh,
            model_axis=model_axis,
            comm=cfg.comm,
            fused=cfg.fused,
            plan=eplan,
            replan=args.replan,
            seed=args.seed,
            eval_data=(ev_imgs, ev_labels),
            checkpoint_dir=args.checkpoint_dir,
            resume=args.resume,
            metrics=metrics,
            loader=args.zoo_loader,
            resilience=cfg.resilience,
            chaos=chaos,
            obs=obs_bundle,
            elastic=cfg.elastic,
            pipeline=cfg.pipeline,
            # Zoo --profile = a jax.profiler trace of 3 steady-state steps
            # of THE run's own jitted step (augment/schedule/accum/mesh
            # included; compile excluded) — the single-chip MFU attribution
            # tool. The lenet path's --profile prints the per-phase table.
            profile_trace_dir=(
                os.path.abspath(
                    os.path.join(args.checkpoint_dir or ".", "zoo_xla_trace")
                )
                if args.profile
                else None
            ),
        )
    for kind, path in obs_bundle.finish().items():
        print(f"[obs] {kind} written to {path}")
    if guard.preempted:
        print("preempted: checkpoint flushed; continue with --resume")
    if metrics:
        metrics.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
