from parallel_cnn_tpu.ops.activations import (  # noqa: F401
    apply_grad,
    error_norm,
    make_error,
    sigmoid,
    sigmoid_grad_from_preact,
)
from parallel_cnn_tpu.ops import pallas, reference  # noqa: F401
