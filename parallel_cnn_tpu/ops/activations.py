"""Activation + loss-gradient utilities (≙ Sequential/layer.h:81-101).

The reference's "step_function" is a logistic sigmoid despite the name
(Sequential/layer.h:81-83); `makeError` produces the (onehot − output) error
vector fed directly into backprop as d_preact (layer.h:91-95); `apply_grad`
is the `w += dt * g` SGD step (layer.h:97-101).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sigmoid(v: jax.Array) -> jax.Array:
    """≙ step_function (Sequential/layer.h:81-83): 1/(1+exp(−v)).

    jax.nn.sigmoid is the numerically-stable fused form XLA lowers well.
    """
    return jax.nn.sigmoid(v)


def sigmoid_grad_from_preact(preact: jax.Array) -> jax.Array:
    """σ′(preact) = σ·(1−σ), recomputed from preact exactly as the reference
    backward kernels do (e.g. bp_preact_s1, Sequential/layer.h:265-266)."""
    s = jax.nn.sigmoid(preact)
    return s * (1.0 - s)


def make_error(output: jax.Array, label: jax.Array, num_classes: int = 10) -> jax.Array:
    """≙ makeError (Sequential/layer.h:91-95): err[i] = onehot(Y)[i] − output[i].

    This is fed straight into backprop as dL/d(preact) of the final layer —
    the reference never materializes a loss value.
    """
    return jax.nn.one_hot(label, num_classes, dtype=output.dtype) - output


def error_norm(err: jax.Array) -> jax.Array:
    """≙ vectorNorm (Sequential/Main.cpp:28-34): ‖err‖₂ — the training metric."""
    return jnp.sqrt(jnp.sum(err * err))


def apply_grad(params, grads, dt: float):
    """≙ apply_grad (Sequential/layer.h:97-101): p += dt·g over a pytree.

    The `+=` sign is correct because makeError already encodes (target −
    output); grads here follow the same convention.
    """
    return jax.tree_util.tree_map(lambda p, g: p + dt * g, params, grads)
