"""Pallas TPU kernel library — the compiled-kernel path (path B).

≙ the CUDA backend's 15 ``__global__`` kernels (CUDA/layer.cu:80-368,
prototypes CUDA/layer_c.h:38-58; SURVEY.md §2.2 C17): where the reference
hand-schedules one CUDA thread per output element, this module hand-schedules
Mosaic kernels over a batch-block grid. It is the "native compiled kernel"
component of the framework — Pallas lowers to Mosaic, the TPU kernel
compiler, exactly as CUDA C++ lowers to SASS.

Design (empirically validated on TPU v5e Mosaic — see probe notes):

- **Batch is the grid.** The reference launches one kernel per *sample*
  (CUDA/main.cu:178-189 inside the 60k loop). On TPU the batch dimension is
  the only one big enough to occupy the machine, so every kernel takes a
  ``(Bb, ...)`` batch block per grid step and the gradient kernels
  *accumulate* partial sums across grid steps into their output block
  (``o_ref[...] += ...`` with a first-step zero-init) — the in-VMEM
  equivalent of the CUDA backend's ``atomicAdd`` trees
  (CUDA/layer.cu:162,196,264) with no atomics needed: the TPU grid is
  sequential on-core.
- **All contractions are rank-2 ``lax.dot_general`` on the MXU**; the 5×5
  conv is 25 unrolled tap-FMAs on the VPU (one vector op per tap, the
  systolic analog of the CUDA output-stationary loop, CUDA/layer.cu:116-130).
- **Layout packing lives in XLA, FLOPs live in Pallas.** This Mosaic
  version supports neither strided slices nor lane-splitting reshapes
  in-kernel, so the stride-4 window gather for the pool layer and the
  im2col patch matrices are built host-side (they are free or cheap
  relayouts XLA already excels at) and the kernels see dense rank-2/3
  blocks. Scalar stores to VMEM are also rejected — every kernel output is
  a vector row or tile; the few true-scalar reductions (bias grads, error
  norm) stay in XLA glue.

Numerics contract is identical to ops/reference.py (SURVEY.md §2.1): same
/576 and /216 grad normalizations, same (onehot − output) error vector.
Differential tests: tests/test_ops_pallas.py diffs this path against the
jnp path A on an 8-device CPU harness in interpret mode.

Flat layout convention: the 6×6×6 pool/FC boundary is flattened
channel-major, lane = m*36 + x*6 + y — the same C-order flatten the
reference uses for l_s1.output → fp_preact_f (Sequential/layer.h:184-198).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from parallel_cnn_tpu.ops import reference as ref_ops
from parallel_cnn_tpu.ops.activations import error_norm, make_error

Params = ref_ops.Params


def _interpret() -> bool:
    """Compiled Mosaic on TPU; interpreter everywhere else (CPU tests).

    Uses utils.backend.is_tpu, NOT `jax.default_backend() == "tpu"`: under
    the axon relay the backend name is "axon" while the hardware is a real
    TPU chip — the naive check would (and in round 1 did) silently run the
    interpreter on real hardware.
    """
    from parallel_cnn_tpu.utils.backend import is_tpu

    return not is_tpu()


def _batch_block(n: int, want: int = 128) -> int:
    """Largest divisor of n that is ≤ want (grid must tile the batch)."""
    b = min(n, want)
    while n % b:
        b -= 1
    return b


# VMEM budget: rank-4 (Bb,6,24,24) blocks pad their lane dim 24→128, so a
# conv-layer block costs 6·24·128·4 B ≈ 74 KB/sample and Pallas double-buffers
# every pipelined block — 32 samples keeps conv kernels ≈ 10 MB < 16 MB VMEM.
# Flat (Bb,216) blocks are ~1 KB/sample and can run much wider.
CONV_BLOCK = 32
FLAT_BLOCK = 256


def _sigmoid(v):
    # jax.nn.sigmoid — the numerically stable two-branch form, same as
    # activations.sigmoid (path A); lowers cleanly in Mosaic.
    return jax.nn.sigmoid(v)


def _pad_batch(n: int, block: int) -> int:
    """Samples of zero-padding needed to reach a multiple of `block`.

    Without padding, awkward batch sizes (primes, dataset remainders) would
    fall back to divisor-of-n blocks as small as 1 — a silent 100× grid
    blow-up. Public entry points pad instead and mask/slice the pad away.
    """
    return (-n) % block


# ---------------------------------------------------------------------------
# Forward kernels
# ---------------------------------------------------------------------------


def _conv_fwd_kernel(x_ref, w_ref, b_ref, pre_ref, out_ref):
    """≙ fp_c1 (CUDA/layer.cu:116-130) + apply_step_function (:85-95), fused.

    One grid step = one batch block. 6 filters × 25 taps unrolled: each tap
    is a (Bb, 24, 24) VPU FMA against a shifted window of the input block —
    output-stationary like the CUDA kernel, but vectorized over the batch
    instead of threaded over output pixels.
    """
    for m in range(6):
        acc = jnp.full(pre_ref.shape[:1] + (24, 24), b_ref[m, 0], pre_ref.dtype)
        for i in range(5):
            for j in range(5):
                acc = acc + w_ref[m, i, j] * x_ref[:, i : i + 24, j : j + 24]
        pre_ref[:, m] = acc
        out_ref[:, m] = _sigmoid(acc)


def conv_fwd(x: jax.Array, w: jax.Array, b: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(B,28,28)·(6,5,5)+(6,) → (pre_c1, out_c1), both (B,6,24,24)."""
    n = x.shape[0]
    bb = _batch_block(n, CONV_BLOCK)
    return pl.pallas_call(
        _conv_fwd_kernel,
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec((bb, 28, 28), lambda g: (g, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((6, 5, 5), lambda g: (0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((6, 1), lambda g: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bb, 6, 24, 24), lambda g: (g, 0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 6, 24, 24), lambda g: (g, 0, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 6, 24, 24), x.dtype),
            jax.ShapeDtypeStruct((n, 6, 24, 24), x.dtype),
        ],
        interpret=_interpret(),
    )(x, w, b.reshape(6, 1))


def pack_pool_windows(out_c1: jax.Array) -> jax.Array:
    """(B,6,24,24) → (B,16,216): stride-4 4×4 windows, tap-major sublane,
    flat channel-major window lane (t = 4i+j, lane = m*36 + x*6 + y).

    Host-side XLA relayout — the stride-4 gather Mosaic can't express
    in-kernel; 24 = 6·4 tiles exactly so it is a pure reshape+transpose.
    """
    b = out_c1.shape[0]
    win = out_c1.reshape(b, 6, 6, 4, 6, 4)          # (b, m, x, i, y, j)
    return win.transpose(0, 3, 5, 1, 2, 4).reshape(b, 16, 216)


def unpack_pool_windows(d_xw: jax.Array) -> jax.Array:
    """Inverse of pack_pool_windows: (B,16,216) → (B,6,24,24)."""
    b = d_xw.shape[0]
    win = d_xw.reshape(b, 4, 4, 6, 6, 6)            # (b, i, j, m, x, y)
    return win.transpose(0, 3, 4, 1, 5, 2).reshape(b, 6, 24, 24)


def _pool_fwd_kernel(xw_ref, w_ref, b_ref, pre_ref, out_ref):
    """≙ fp_s1 (CUDA/layer.cu:132-149) + sigmoid, fused.

    16 tap-FMAs over the packed (Bb, 16, 216) window block: tap t rides the
    sublane-adjacent dim, the 216 pool outputs ride the lane dim.
    """
    acc = jnp.full(pre_ref.shape, b_ref[0, 0], pre_ref.dtype)
    for t in range(16):
        acc = acc + w_ref[t, 0] * xw_ref[:, t, :]
    pre_ref[:] = acc
    out_ref[:] = _sigmoid(acc)


def pool_fwd(xw: jax.Array, w: jax.Array, b: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(B,16,216)·(4,4)+() → (pre_s1, out_s1), both (B,216) flat channel-major."""
    n = xw.shape[0]
    bb = _batch_block(n, FLAT_BLOCK)
    return pl.pallas_call(
        _pool_fwd_kernel,
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec((bb, 16, 216), lambda g: (g, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((16, 1), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda g: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 216), xw.dtype),
            jax.ShapeDtypeStruct((n, 216), xw.dtype),
        ],
        interpret=_interpret(),
    )(xw, w.reshape(16, 1), b.reshape(1, 1))


def _fc_fwd_kernel(x_ref, w_ref, b_ref, pre_ref, out_ref):
    """≙ fp_f (CUDA/layer.cu:151-165, minus bug B10's redundant launch):
    one MXU contraction (Bb,216)·(10,216)ᵀ per block + bias row."""
    acc = lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=pre_ref.dtype,
        precision=lax.Precision.HIGHEST,
    ) + b_ref[:]
    pre_ref[:] = acc
    out_ref[:] = _sigmoid(acc)


def fc_fwd(x: jax.Array, w: jax.Array, b: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(B,216)·(10,216)+(10,) → (pre_f, out_f), both (B,10)."""
    n = x.shape[0]
    bb = _batch_block(n, FLAT_BLOCK)
    return pl.pallas_call(
        _fc_fwd_kernel,
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((10, 216), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 10), lambda g: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bb, 10), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 10), lambda g: (g, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 10), x.dtype),
            jax.ShapeDtypeStruct((n, 10), x.dtype),
        ],
        interpret=_interpret(),
    )(x, w, b.reshape(1, 10))


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _fc_bwd_kernel(d_ref, s_ref, w_ref, gw_ref, gb_ref, dout_ref):
    """≙ bp_weight_f + bp_bias_f + bp_output_s1 (CUDA/layer.cu:167-216), fused.

    Weight grad: (10,Bb)·(Bb,216) MXU outer-product partial, accumulated
    across the batch grid (≙ the CUDA atomicAdd, layer.cu:196). Also emits
    d_out_s1 = d_pre_f · W for the next stage in the same pass.
    """
    @pl.when(pl.program_id(0) == 0)
    def _():
        gw_ref[:] = jnp.zeros_like(gw_ref)
        gb_ref[:] = jnp.zeros_like(gb_ref)

    d = d_ref[:]
    gw_ref[:] += lax.dot_general(
        d, s_ref[:], (((0,), (0,)), ((), ())), preferred_element_type=gw_ref.dtype,
        precision=lax.Precision.HIGHEST,
    )
    gb_ref[:] += jnp.sum(d, axis=0, keepdims=True)
    dout_ref[:] = lax.dot_general(
        d, w_ref[:], (((1,), (0,)), ((), ())), preferred_element_type=dout_ref.dtype,
        precision=lax.Precision.HIGHEST,
    )


def fc_bwd(
    d_pre_f: jax.Array, out_s1: jax.Array, w: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(B,10),(B,216),(10,216) → (g_w_f (10,216) summed over batch,
    g_b_f (10,) summed, d_out_s1 (B,216))."""
    n = d_pre_f.shape[0]
    bb = _batch_block(n, FLAT_BLOCK)
    gw, gb, dout = pl.pallas_call(
        _fc_bwd_kernel,
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec((bb, 10), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((10, 216), lambda g: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((10, 216), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 10), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((10, 216), d_pre_f.dtype),
            jax.ShapeDtypeStruct((1, 10), d_pre_f.dtype),
            jax.ShapeDtypeStruct((n, 216), d_pre_f.dtype),
        ],
        interpret=_interpret(),
    )(d_pre_f, out_s1, w)
    return gw, gb.reshape(10), dout


def _pool_bwd_kernel(dout_ref, pre_ref, w_ref, dpre_ref, dxw_ref):
    """≙ bp_preact_s1 + bp_output_c1 (CUDA/layer.cu:230-254), fused:
    σ′ chain through the pool preact, then scatter through the shared 4×4
    kernel into window layout (the strided scatter the CUDA kernel does
    one-thread-per-element; here one VPU row per tap)."""
    s = _sigmoid(pre_ref[:])
    dpre = dout_ref[:] * s * (1.0 - s)
    dpre_ref[:] = dpre
    for t in range(16):
        dxw_ref[:, t, :] = w_ref[t, 0] * dpre


def pool_bwd(
    d_out_s1: jax.Array, pre_s1: jax.Array, w: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """(B,216),(B,216),(4,4) → (d_pre_s1 (B,216), d_xw (B,16,216))."""
    n = d_out_s1.shape[0]
    bb = _batch_block(n, FLAT_BLOCK)
    return pl.pallas_call(
        _pool_bwd_kernel,
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((16, 1), lambda g: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 16, 216), lambda g: (g, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 216), d_out_s1.dtype),
            jax.ShapeDtypeStruct((n, 16, 216), d_out_s1.dtype),
        ],
        interpret=_interpret(),
    )(d_out_s1, pre_s1, w.reshape(16, 1))


def _accum_matmul_kernel(a_ref, b_ref, o_ref):
    """Grid-accumulated Aᵀ·B: the generic weight-grad contraction
    (≙ the CUDA backward weight kernels' atomicAdd reductions)."""
    @pl.when(pl.program_id(0) == 0)
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)

    o_ref[:] += lax.dot_general(
        a_ref[:], b_ref[:], (((0,), (0,)), ((), ())), preferred_element_type=o_ref.dtype,
        precision=lax.Precision.HIGHEST,
    )


def _accum_matmul(a: jax.Array, b: jax.Array, row_block: int) -> jax.Array:
    """(N,ka),(N,kb) → (ka,kb) = Σ_n a[n,:]ᵀ b[n,:], grid over row chunks."""
    n = a.shape[0]
    rb = _batch_block(n, row_block)
    ka, kb = a.shape[1], b.shape[1]
    return pl.pallas_call(
        _accum_matmul_kernel,
        grid=(n // rb,),
        in_specs=[
            pl.BlockSpec((rb, ka), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, kb), lambda g: (g, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ka, kb), lambda g: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((ka, kb), a.dtype),
        interpret=_interpret(),
    )(a, b)


def pool_wgrad(out_c1_windows: jax.Array, d_pre_s1: jax.Array) -> jax.Array:
    """≙ bp_weight_s1 (CUDA/layer.cu:218-228): g_w_s1[i,j] = Σ_{b,w}
    d_pre_s1[b,w] · windows[b,4i+j,w], as one (B·216,16)ᵀ·(B·216,1) MXU
    contraction accumulated over row chunks."""
    b = out_c1_windows.shape[0]
    xw2 = out_c1_windows.transpose(0, 2, 1).reshape(b * 216, 16)
    dp2 = d_pre_s1.reshape(b * 216, 1)
    g = _accum_matmul(xw2, dp2, row_block=216 * 8)
    return g.reshape(4, 4)


def _sigma_prime_kernel(dout_ref, pre_ref, o_ref):
    """≙ bp_preact_c1 (CUDA/layer.cu:292-305): d_pre = d_out · σ′(pre)."""
    s = _sigmoid(pre_ref[:])
    o_ref[:] = dout_ref[:] * s * (1.0 - s)


def conv_bwd_dpre(d_out_c1: jax.Array, pre_c1: jax.Array) -> jax.Array:
    """(B,6,24,24) σ′ chain, elementwise on the VPU."""
    n = d_out_c1.shape[0]
    bb = _batch_block(n, CONV_BLOCK)
    return pl.pallas_call(
        _sigma_prime_kernel,
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec((bb, 6, 24, 24), lambda g: (g, 0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 6, 24, 24), lambda g: (g, 0, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bb, 6, 24, 24), lambda g: (g, 0, 0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(d_out_c1.shape, d_out_c1.dtype),
        interpret=_interpret(),
    )(d_out_c1, pre_c1)


def conv_wgrad(x: jax.Array, d_pre_c1: jax.Array) -> jax.Array:
    """≙ bp_weight_c1 (CUDA/layer.cu:307-335): /576-normalized correlation
    of d_pre_c1 with the input patches, as a (B·576,6)ᵀ·(B·576,25) MXU
    contraction. im2col (patch matrix) is host-side XLA."""
    b = x.shape[0]
    # (B, 25, 24, 24): feature dim = 5i+j tap order (1 input channel)
    patches = lax.conv_general_dilated_patches(x[:, None], (5, 5), (1, 1), "VALID")
    pm = patches.transpose(0, 2, 3, 1).reshape(b * 576, 25)
    dpm = d_pre_c1.transpose(0, 2, 3, 1).reshape(b * 576, 6)
    g = _accum_matmul(dpm, pm, row_block=576 * 8)  # (6, 25)
    return g.reshape(6, 5, 5) / ref_ops.CONV_NORM


# ---------------------------------------------------------------------------
# Full batched forward / backward on the Pallas path
# ---------------------------------------------------------------------------


def _forward_flat(params: Params, xs: jax.Array):
    """The shared three-stage Pallas forward pipeline (flat pool/FC layout).

    Returns (pre_c1, out_c1, xw, pre_s1, out_s1, pre_f, out_f) with the
    pool/FC stages in (B,216) flat channel-major layout. The batch must
    already be a multiple of CONV_BLOCK (public entry points pad)."""
    pre_c1, out_c1 = conv_fwd(xs, params["c1"]["w"], params["c1"]["b"])
    xw = pack_pool_windows(out_c1)
    pre_s1, out_s1 = pool_fwd(xw, params["s1"]["w"], params["s1"]["b"])
    pre_f, out_f = fc_fwd(out_s1, params["f"]["w"], params["f"]["b"])
    return pre_c1, out_c1, xw, pre_s1, out_s1, pre_f, out_f


def forward(params: Params, xs: jax.Array):
    """Batched forward through the three Pallas stages.

    Returns the same Activations tuple as ops/reference.py:forward (batched,
    pool/FC stages in flat channel-major layout reshaped back to (6,6,6))."""
    n = xs.shape[0]
    pad = _pad_batch(n, CONV_BLOCK)
    if pad:
        xs = jnp.concatenate([xs, jnp.zeros((pad,) + xs.shape[1:], xs.dtype)])
    pre_c1, out_c1, _, pre_s1, out_s1, pre_f, out_f = _forward_flat(params, xs)
    np_ = n + pad
    acts = ref_ops.Activations(
        xs,
        pre_c1,
        out_c1,
        pre_s1.reshape(np_, 6, 6, 6),
        out_s1.reshape(np_, 6, 6, 6),
        pre_f,
        out_f,
    )
    if pad:
        acts = ref_ops.Activations(*(a[:n] for a in acts))
    return acts


def predict(params: Params, xs: jax.Array) -> jax.Array:
    """≙ classify (CUDA/main.cu:200-223): batched argmax over the outputs."""
    return jnp.argmax(forward(params, xs).out_f, axis=-1)


def batched_value_and_ref_grads(
    params: Params, xs: jax.Array, ys: jax.Array
) -> Tuple[jax.Array, Params]:
    """(err_mean, batch-mean reference grads) on the Pallas path.

    Matches jax.vmap(ops.reference.value_and_ref_grads) + tree-mean to fp
    tolerance; same reference contract (SURVEY.md §2.1), kernels instead of
    XLA ops for every FLOP-bearing stage. Batches that don't tile
    CONV_BLOCK are zero-padded; padded rows are masked out of the error
    vector, so every grad contribution below is exactly zero for them.
    """
    n = xs.shape[0]
    pad = _pad_batch(n, CONV_BLOCK)
    if pad:
        xs = jnp.concatenate([xs, jnp.zeros((pad,) + xs.shape[1:], xs.dtype)])
        ys = jnp.concatenate([ys, jnp.zeros((pad,), ys.dtype)])

    pre_c1, out_c1, xw, pre_s1, out_s1, pre_f, out_f = _forward_flat(params, xs)

    # makeError + vectorNorm (host glue: O(B·10))
    d_pre_f = jax.vmap(make_error)(out_f, ys)
    if pad:
        mask = (jnp.arange(n + pad) < n).astype(d_pre_f.dtype)
        d_pre_f = d_pre_f * mask[:, None]
    err_mean = jnp.sum(jax.vmap(error_norm)(d_pre_f)) / n

    g_w_f, g_b_f, d_out_s1 = fc_bwd(d_pre_f, out_s1, params["f"]["w"])
    d_pre_s1, d_xw = pool_bwd(d_out_s1, pre_s1, params["s1"]["w"])
    g_w_s1 = pool_wgrad(xw, d_pre_s1)
    # bp_bias_s1 (CUDA/layer.cu:256-266, minus bug B9): mean over all 216
    g_b_s1 = jnp.sum(d_pre_s1) / ref_ops.POOL_BIAS_NORM

    d_out_c1 = unpack_pool_windows(d_xw)
    d_pre_c1 = conv_bwd_dpre(d_out_c1, pre_c1)
    g_w_c1 = conv_wgrad(xs, d_pre_c1)
    # bp_bias_c1 (CUDA/layer.cu:337-368): /576-normalized per-filter mean
    g_b_c1 = jnp.sum(d_pre_c1, axis=(0, 2, 3)) / ref_ops.CONV_NORM

    inv_n = 1.0 / n
    grads: Params = {
        "c1": {"w": g_w_c1 * inv_n, "b": g_b_c1 * inv_n},
        "s1": {"w": g_w_s1 * inv_n, "b": g_b_s1 * inv_n},
        "f": {"w": g_w_f * inv_n, "b": g_b_f * inv_n},
    }
    return err_mean, grads
