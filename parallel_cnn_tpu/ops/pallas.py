"""Pallas TPU kernel library — the compiled-kernel path (path B).

≙ the CUDA backend's 15 ``__global__`` kernels (CUDA/layer.cu:80-368,
prototypes CUDA/layer_c.h:38-58; SURVEY.md §2.2 C17): where the reference
hand-schedules one CUDA thread per output element, this module hand-schedules
Mosaic kernels over a batch-block grid. It is the "native compiled kernel"
component of the framework — Pallas lowers to Mosaic, the TPU kernel
compiler, exactly as CUDA C++ lowers to SASS.

Two tiers, both compiled Mosaic on TPU:

1. **Per-op kernel library** (conv_fwd … conv_wgrad, staged_…): one
   pallas_call per reference kernel — the direct structural analog of the
   CUDA backend's launch-per-kernel driver (CUDA/main.cu:110-159).
2. **Fused megakernel** (`fused_value_and_ref_grads`, the product fast
   path): the ENTIRE step's math in one pallas_call — round-2 measurement
   showed the staged tier 6.3× slower than XLA path A because per-call
   pipeline overhead + HBM round-trips dominate a 379-kFLOP model; the
   fused tier beats path A on-chip (BENCH_r03).

Design (empirically validated on TPU v5e Mosaic — see probe notes):

- **Batch is the grid.** The reference launches one kernel per *sample*
  (CUDA/main.cu:178-189 inside the 60k loop). On TPU the batch dimension is
  the only one big enough to occupy the machine, so every kernel takes a
  ``(Bb, ...)`` batch block per grid step and the gradient kernels
  *accumulate* partial sums across grid steps into their output block
  (``o_ref[...] += ...`` with a first-step zero-init) — the in-VMEM
  equivalent of the CUDA backend's ``atomicAdd`` trees
  (CUDA/layer.cu:162,196,264) with no atomics needed: the TPU grid is
  sequential on-core.
- **All contractions are rank-2 ``lax.dot_general`` on the MXU**; the 5×5
  conv is 25 unrolled tap-FMAs on the VPU (one vector op per tap, the
  systolic analog of the CUDA output-stationary loop, CUDA/layer.cu:116-130).
- **Layout packing lives in XLA, FLOPs live in Pallas.** Mosaic supports
  neither strided slices nor lane-splitting reshapes in-kernel, so the
  staged tier builds the stride-4 pool windows and im2col patch matrices
  host-side; the fused tier goes further and picks layouts that need no
  packing at all (flat-576 lanes + the Mp scatter-matmul — see the fused
  section). Scalar stores to VMEM are also rejected, and so are rank-1
  vector relayouts — every kernel value stays rank-2+, and the few
  true-scalar reductions (bias grads, error norm) stay in XLA glue.

Numerics contract is identical to ops/reference.py (SURVEY.md §2.1): same
/576 and /216 grad normalizations, same (onehot − output) error vector.
Differential tests: tests/test_ops_pallas.py diffs both tiers against the
jnp path A on an 8-device CPU harness in interpret mode; bench.py diffs
the fused tier on-chip (`pallas_max_abs_diff`).

Flat layout convention: the 6×6×6 pool/FC boundary is flattened
channel-major, lane = m*36 + x*6 + y — the same C-order flatten the
reference uses for l_s1.output → fp_preact_f (Sequential/layer.h:184-198).
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from parallel_cnn_tpu.ops import reference as ref_ops
from parallel_cnn_tpu.ops.activations import error_norm, make_error

Params = ref_ops.Params


def _interpret() -> bool:
    """Compiled Mosaic on TPU; interpreter everywhere else (CPU tests).

    Uses utils.backend.is_tpu, NOT `jax.default_backend() == "tpu"`: under
    the axon relay the backend name is "axon" while the hardware is a real
    TPU chip — the naive check would (and in round 1 did) silently run the
    interpreter on real hardware.
    """
    from parallel_cnn_tpu.utils.backend import is_tpu

    return not is_tpu()


# Test hook: force the fused path's x25 operand to stay f32 even when
# compiled (the bf16 store is a measured-zero-cost optimization that rests
# on an XLA lowering detail — see fused_value_and_ref_grads). Monkeypatched
# by test_fused_bf16_store_vs_f32_store to diff the two stores on-chip.
_FORCE_X25_F32 = False

# Forward-conv engine inside the fused megakernel: the r5 on-chip probes
# (docs/mosaic_probe_r5.txt) measured a (6,25)@(25,·) MXU dot 7× faster
# than the 150-FMA VPU loop, and the rank-2×rank-3 form
# (6,25)@(25,Bb,576) → (6,Bb,576) needs NO relayout on either side — a
# drop-in swap for the per-filter tap loop. Env-gated (read at import)
# while the compiled lowering + parity are being established on-chip;
# tests flip the module attribute via monkeypatch instead
# (test_fused_mxu_conv_engine_matches — the kernel reads this global at
# trace time, so a fresh jit after patching picks it up).
_MXU_CONV = os.environ.get("PCNN_FUSED_MXU_CONV", "0") == "1"  # graftcheck: disable=env-outside-config -- import-time kernel gate read into a trace-time global by design (see comment above)


def _batch_block(n: int, want: int = 128) -> int:
    """Largest divisor of n that is ≤ want (grid must tile the batch)."""
    b = min(n, want)
    while n % b:
        b -= 1
    return b


# VMEM budget: rank-4 (Bb,6,24,24) blocks pad their lane dim 24→128, so a
# conv-layer block costs 6·24·128·4 B ≈ 74 KB/sample and Pallas double-buffers
# every pipelined block — 32 samples keeps conv kernels ≈ 10 MB < 16 MB VMEM.
# Flat (Bb,216) blocks are ~1 KB/sample and can run much wider.
CONV_BLOCK = 32
FLAT_BLOCK = 256


def _sigmoid(v):
    # jax.nn.sigmoid — the numerically stable two-branch form, same as
    # activations.sigmoid (path A); lowers cleanly in Mosaic.
    return jax.nn.sigmoid(v)


def _pad_batch(n: int, block: int) -> int:
    """Samples of zero-padding needed to reach a multiple of `block`.

    Without padding, awkward batch sizes (primes, dataset remainders) would
    fall back to divisor-of-n blocks as small as 1 — a silent 100× grid
    blow-up. Public entry points pad instead and mask/slice the pad away.
    """
    return (-n) % block


# ---------------------------------------------------------------------------
# Forward kernels
# ---------------------------------------------------------------------------


def _conv_fwd_kernel(x_ref, w_ref, b_ref, pre_ref, out_ref):
    """≙ fp_c1 (CUDA/layer.cu:116-130) + apply_step_function (:85-95), fused.

    One grid step = one batch block. 6 filters × 25 taps unrolled: each tap
    is a (Bb, 24, 24) VPU FMA against a shifted window of the input block —
    output-stationary like the CUDA kernel, but vectorized over the batch
    instead of threaded over output pixels.
    """
    for m in range(6):
        acc = jnp.full(pre_ref.shape[:1] + (24, 24), b_ref[m, 0], pre_ref.dtype)
        for i in range(5):
            for j in range(5):
                acc = acc + w_ref[m, i, j] * x_ref[:, i : i + 24, j : j + 24]
        pre_ref[:, m] = acc
        out_ref[:, m] = _sigmoid(acc)


def conv_fwd(x: jax.Array, w: jax.Array, b: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(B,28,28)·(6,5,5)+(6,) → (pre_c1, out_c1), both (B,6,24,24)."""
    n = x.shape[0]
    bb = _batch_block(n, CONV_BLOCK)
    return pl.pallas_call(
        _conv_fwd_kernel,
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec((bb, 28, 28), lambda g: (g, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((6, 5, 5), lambda g: (0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((6, 1), lambda g: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bb, 6, 24, 24), lambda g: (g, 0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 6, 24, 24), lambda g: (g, 0, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 6, 24, 24), x.dtype),
            jax.ShapeDtypeStruct((n, 6, 24, 24), x.dtype),
        ],
        interpret=_interpret(),
    )(x, w, b.reshape(6, 1))


def pack_pool_windows(out_c1: jax.Array) -> jax.Array:
    """(B,6,24,24) → (B,16,216): stride-4 4×4 windows, tap-major sublane,
    flat channel-major window lane (t = 4i+j, lane = m*36 + x*6 + y).

    Host-side XLA relayout — the stride-4 gather Mosaic can't express
    in-kernel; 24 = 6·4 tiles exactly so it is a pure reshape+transpose.
    """
    b = out_c1.shape[0]
    win = out_c1.reshape(b, 6, 6, 4, 6, 4)          # (b, m, x, i, y, j)
    return win.transpose(0, 3, 5, 1, 2, 4).reshape(b, 16, 216)


def unpack_pool_windows(d_xw: jax.Array) -> jax.Array:
    """Inverse of pack_pool_windows: (B,16,216) → (B,6,24,24)."""
    b = d_xw.shape[0]
    win = d_xw.reshape(b, 4, 4, 6, 6, 6)            # (b, i, j, m, x, y)
    return win.transpose(0, 3, 4, 1, 5, 2).reshape(b, 6, 24, 24)


def _pool_fwd_kernel(xw_ref, w_ref, b_ref, pre_ref, out_ref):
    """≙ fp_s1 (CUDA/layer.cu:132-149) + sigmoid, fused.

    16 tap-FMAs over the packed (Bb, 16, 216) window block: tap t rides the
    sublane-adjacent dim, the 216 pool outputs ride the lane dim.
    """
    acc = jnp.full(pre_ref.shape, b_ref[0, 0], pre_ref.dtype)
    for t in range(16):
        acc = acc + w_ref[t, 0] * xw_ref[:, t, :]
    pre_ref[:] = acc
    out_ref[:] = _sigmoid(acc)


def pool_fwd(xw: jax.Array, w: jax.Array, b: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(B,16,216)·(4,4)+() → (pre_s1, out_s1), both (B,216) flat channel-major."""
    n = xw.shape[0]
    bb = _batch_block(n, FLAT_BLOCK)
    return pl.pallas_call(
        _pool_fwd_kernel,
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec((bb, 16, 216), lambda g: (g, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((16, 1), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda g: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 216), xw.dtype),
            jax.ShapeDtypeStruct((n, 216), xw.dtype),
        ],
        interpret=_interpret(),
    )(xw, w.reshape(16, 1), b.reshape(1, 1))


def _fc_fwd_kernel(x_ref, w_ref, b_ref, pre_ref, out_ref):
    """≙ fp_f (CUDA/layer.cu:151-165, minus bug B10's redundant launch):
    one MXU contraction (Bb,216)·(10,216)ᵀ per block + bias row."""
    acc = lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=pre_ref.dtype,
        precision=lax.Precision.HIGHEST,
    ) + b_ref[:]
    pre_ref[:] = acc
    out_ref[:] = _sigmoid(acc)


def fc_fwd(x: jax.Array, w: jax.Array, b: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(B,216)·(10,216)+(10,) → (pre_f, out_f), both (B,10)."""
    n = x.shape[0]
    bb = _batch_block(n, FLAT_BLOCK)
    return pl.pallas_call(
        _fc_fwd_kernel,
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((10, 216), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 10), lambda g: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bb, 10), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 10), lambda g: (g, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 10), x.dtype),
            jax.ShapeDtypeStruct((n, 10), x.dtype),
        ],
        interpret=_interpret(),
    )(x, w, b.reshape(1, 10))


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _fc_bwd_kernel(d_ref, s_ref, w_ref, gw_ref, gb_ref, dout_ref):
    """≙ bp_weight_f + bp_bias_f + bp_output_s1 (CUDA/layer.cu:167-216), fused.

    Weight grad: (10,Bb)·(Bb,216) MXU outer-product partial, accumulated
    across the batch grid (≙ the CUDA atomicAdd, layer.cu:196). Also emits
    d_out_s1 = d_pre_f · W for the next stage in the same pass.
    """
    @pl.when(pl.program_id(0) == 0)
    def _():
        gw_ref[:] = jnp.zeros_like(gw_ref)
        gb_ref[:] = jnp.zeros_like(gb_ref)

    d = d_ref[:]
    gw_ref[:] += lax.dot_general(
        d, s_ref[:], (((0,), (0,)), ((), ())), preferred_element_type=gw_ref.dtype,
        precision=lax.Precision.HIGHEST,
    )
    gb_ref[:] += jnp.sum(d, axis=0, keepdims=True)
    dout_ref[:] = lax.dot_general(
        d, w_ref[:], (((1,), (0,)), ((), ())), preferred_element_type=dout_ref.dtype,
        precision=lax.Precision.HIGHEST,
    )


def fc_bwd(
    d_pre_f: jax.Array, out_s1: jax.Array, w: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(B,10),(B,216),(10,216) → (g_w_f (10,216) summed over batch,
    g_b_f (10,) summed, d_out_s1 (B,216))."""
    n = d_pre_f.shape[0]
    bb = _batch_block(n, FLAT_BLOCK)
    gw, gb, dout = pl.pallas_call(
        _fc_bwd_kernel,
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec((bb, 10), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((10, 216), lambda g: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((10, 216), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 10), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((10, 216), d_pre_f.dtype),
            jax.ShapeDtypeStruct((1, 10), d_pre_f.dtype),
            jax.ShapeDtypeStruct((n, 216), d_pre_f.dtype),
        ],
        interpret=_interpret(),
    )(d_pre_f, out_s1, w)
    return gw, gb.reshape(10), dout


def _pool_bwd_kernel(dout_ref, pre_ref, w_ref, dpre_ref, dxw_ref):
    """≙ bp_preact_s1 + bp_output_c1 (CUDA/layer.cu:230-254), fused:
    σ′ chain through the pool preact, then scatter through the shared 4×4
    kernel into window layout (the strided scatter the CUDA kernel does
    one-thread-per-element; here one VPU row per tap)."""
    s = _sigmoid(pre_ref[:])
    dpre = dout_ref[:] * s * (1.0 - s)
    dpre_ref[:] = dpre
    for t in range(16):
        dxw_ref[:, t, :] = w_ref[t, 0] * dpre


def pool_bwd(
    d_out_s1: jax.Array, pre_s1: jax.Array, w: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """(B,216),(B,216),(4,4) → (d_pre_s1 (B,216), d_xw (B,16,216))."""
    n = d_out_s1.shape[0]
    bb = _batch_block(n, FLAT_BLOCK)
    return pl.pallas_call(
        _pool_bwd_kernel,
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((16, 1), lambda g: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bb, 216), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 16, 216), lambda g: (g, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 216), d_out_s1.dtype),
            jax.ShapeDtypeStruct((n, 16, 216), d_out_s1.dtype),
        ],
        interpret=_interpret(),
    )(d_out_s1, pre_s1, w.reshape(16, 1))


def _accum_matmul_kernel(a_ref, b_ref, o_ref):
    """Grid-accumulated Aᵀ·B: the generic weight-grad contraction
    (≙ the CUDA backward weight kernels' atomicAdd reductions)."""
    @pl.when(pl.program_id(0) == 0)
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)

    o_ref[:] += lax.dot_general(
        a_ref[:], b_ref[:], (((0,), (0,)), ((), ())), preferred_element_type=o_ref.dtype,
        precision=lax.Precision.HIGHEST,
    )


def _accum_matmul(a: jax.Array, b: jax.Array, row_block: int) -> jax.Array:
    """(N,ka),(N,kb) → (ka,kb) = Σ_n a[n,:]ᵀ b[n,:], grid over row chunks."""
    n = a.shape[0]
    rb = _batch_block(n, row_block)
    ka, kb = a.shape[1], b.shape[1]
    return pl.pallas_call(
        _accum_matmul_kernel,
        grid=(n // rb,),
        in_specs=[
            pl.BlockSpec((rb, ka), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, kb), lambda g: (g, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ka, kb), lambda g: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((ka, kb), a.dtype),
        interpret=_interpret(),
    )(a, b)


def pool_wgrad(out_c1_windows: jax.Array, d_pre_s1: jax.Array) -> jax.Array:
    """≙ bp_weight_s1 (CUDA/layer.cu:218-228): g_w_s1[i,j] = Σ_{b,w}
    d_pre_s1[b,w] · windows[b,4i+j,w], as one (B·216,16)ᵀ·(B·216,1) MXU
    contraction accumulated over row chunks."""
    b = out_c1_windows.shape[0]
    xw2 = out_c1_windows.transpose(0, 2, 1).reshape(b * 216, 16)
    dp2 = d_pre_s1.reshape(b * 216, 1)
    g = _accum_matmul(xw2, dp2, row_block=216 * 8)
    return g.reshape(4, 4)


def _sigma_prime_kernel(dout_ref, pre_ref, o_ref):
    """≙ bp_preact_c1 (CUDA/layer.cu:292-305): d_pre = d_out · σ′(pre)."""
    s = _sigmoid(pre_ref[:])
    o_ref[:] = dout_ref[:] * s * (1.0 - s)


def conv_bwd_dpre(d_out_c1: jax.Array, pre_c1: jax.Array) -> jax.Array:
    """(B,6,24,24) σ′ chain, elementwise on the VPU."""
    n = d_out_c1.shape[0]
    bb = _batch_block(n, CONV_BLOCK)
    return pl.pallas_call(
        _sigma_prime_kernel,
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec((bb, 6, 24, 24), lambda g: (g, 0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 6, 24, 24), lambda g: (g, 0, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bb, 6, 24, 24), lambda g: (g, 0, 0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(d_out_c1.shape, d_out_c1.dtype),
        interpret=_interpret(),
    )(d_out_c1, pre_c1)


def conv_wgrad(x: jax.Array, d_pre_c1: jax.Array) -> jax.Array:
    """≙ bp_weight_c1 (CUDA/layer.cu:307-335): /576-normalized correlation
    of d_pre_c1 with the input patches, as a (B·576,6)ᵀ·(B·576,25) MXU
    contraction. im2col (patch matrix) is host-side XLA."""
    b = x.shape[0]
    # (B, 25, 24, 24): feature dim = 5i+j tap order (1 input channel)
    patches = lax.conv_general_dilated_patches(x[:, None], (5, 5), (1, 1), "VALID")
    pm = patches.transpose(0, 2, 3, 1).reshape(b * 576, 25)
    dpm = d_pre_c1.transpose(0, 2, 3, 1).reshape(b * 576, 6)
    g = _accum_matmul(dpm, pm, row_block=576 * 8)  # (6, 25)
    return g.reshape(6, 5, 5) / ref_ops.CONV_NORM


# ---------------------------------------------------------------------------
# Full batched forward / backward on the Pallas path
# ---------------------------------------------------------------------------


def _forward_flat(params: Params, xs: jax.Array):
    """The shared three-stage Pallas forward pipeline (flat pool/FC layout).

    Returns (pre_c1, out_c1, xw, pre_s1, out_s1, pre_f, out_f) with the
    pool/FC stages in (B,216) flat channel-major layout. The batch must
    already be a multiple of CONV_BLOCK (public entry points pad)."""
    pre_c1, out_c1 = conv_fwd(xs, params["c1"]["w"], params["c1"]["b"])
    xw = pack_pool_windows(out_c1)
    pre_s1, out_s1 = pool_fwd(xw, params["s1"]["w"], params["s1"]["b"])
    pre_f, out_f = fc_fwd(out_s1, params["f"]["w"], params["f"]["b"])
    return pre_c1, out_c1, xw, pre_s1, out_s1, pre_f, out_f


def forward(params: Params, xs: jax.Array):
    """Batched forward through the three Pallas stages.

    Returns the same Activations tuple as ops/reference.py:forward (batched,
    pool/FC stages in flat channel-major layout reshaped back to (6,6,6))."""
    n = xs.shape[0]
    pad = _pad_batch(n, CONV_BLOCK)
    if pad:
        xs = jnp.concatenate([xs, jnp.zeros((pad,) + xs.shape[1:], xs.dtype)])
    pre_c1, out_c1, _, pre_s1, out_s1, pre_f, out_f = _forward_flat(params, xs)
    np_ = n + pad
    acts = ref_ops.Activations(
        xs,
        pre_c1,
        out_c1,
        pre_s1.reshape(np_, 6, 6, 6),
        out_s1.reshape(np_, 6, 6, 6),
        pre_f,
        out_f,
    )
    if pad:
        acts = ref_ops.Activations(*(a[:n] for a in acts))
    return acts


def predict(params: Params, xs: jax.Array) -> jax.Array:
    """≙ classify (CUDA/main.cu:200-223): batched argmax over the outputs."""
    return jnp.argmax(forward(params, xs).out_f, axis=-1)


def staged_value_and_ref_grads(
    params: Params, xs: jax.Array, ys: jax.Array
) -> Tuple[jax.Array, Params]:
    """(err_mean, batch-mean reference grads) on the per-op kernel library.

    One pallas_call per reference kernel (≙ the CUDA backend's one launch
    per __global__ kernel, CUDA/main.cu:110-159) with HBM round-trips
    between stages — kept as the kernel-library composition surface and the
    differential anchor for the fused megakernel below, which is the
    product fast path. Matches jax.vmap(ops.reference.value_and_ref_grads)
    + tree-mean to fp tolerance; same reference contract (SURVEY.md §2.1).
    Batches that don't tile CONV_BLOCK are zero-padded; padded rows are
    masked out of the error vector, so every grad contribution below is
    exactly zero for them.
    """
    n = xs.shape[0]
    pad = _pad_batch(n, CONV_BLOCK)
    if pad:
        xs = jnp.concatenate([xs, jnp.zeros((pad,) + xs.shape[1:], xs.dtype)])
        ys = jnp.concatenate([ys, jnp.zeros((pad,), ys.dtype)])

    pre_c1, out_c1, xw, pre_s1, out_s1, pre_f, out_f = _forward_flat(params, xs)

    # makeError + vectorNorm (host glue: O(B·10))
    d_pre_f = jax.vmap(make_error)(out_f, ys)
    if pad:
        mask = (jnp.arange(n + pad) < n).astype(d_pre_f.dtype)
        d_pre_f = d_pre_f * mask[:, None]
    err_mean = jnp.sum(jax.vmap(error_norm)(d_pre_f)) / n

    g_w_f, g_b_f, d_out_s1 = fc_bwd(d_pre_f, out_s1, params["f"]["w"])
    d_pre_s1, d_xw = pool_bwd(d_out_s1, pre_s1, params["s1"]["w"])
    g_w_s1 = pool_wgrad(xw, d_pre_s1)
    # bp_bias_s1 (CUDA/layer.cu:256-266, minus bug B9): mean over all 216
    g_b_s1 = jnp.sum(d_pre_s1) / ref_ops.POOL_BIAS_NORM

    d_out_c1 = unpack_pool_windows(d_xw)
    d_pre_c1 = conv_bwd_dpre(d_out_c1, pre_c1)
    g_w_c1 = conv_wgrad(xs, d_pre_c1)
    # bp_bias_c1 (CUDA/layer.cu:337-368): /576-normalized per-filter mean
    g_b_c1 = jnp.sum(d_pre_c1, axis=(0, 2, 3)) / ref_ops.CONV_NORM

    inv_n = 1.0 / n
    grads: Params = {
        "c1": {"w": g_w_c1 * inv_n, "b": g_b_c1 * inv_n},
        "s1": {"w": g_w_s1 * inv_n, "b": g_b_s1 * inv_n},
        "f": {"w": g_w_f * inv_n, "b": g_b_f * inv_n},
    }
    return err_mean, grads


# ---------------------------------------------------------------------------
# Fused megakernel — the whole train-step math in ONE pallas_call
# ---------------------------------------------------------------------------
#
# ≙ the CUDA backend's fused fp_f/bp_f kernels taken to their logical end
# (CUDA/layer.cu:151-198 already fuses preact+bias+activation; the rest of
# its step is 12 separate launches, CUDA/main.cu:110-159). Round-2 evidence
# (BENCH_r02): the staged 7-call composition ran 6.3× SLOWER than XLA path A
# because per-call pipeline overheads + HBM round-trips dominate a 379-kFLOP
# model. This kernel keeps every intermediate in VMEM for the life of a
# batch block and crosses HBM exactly once per tensor.
#
# Layout strategy (the part Mosaic dictates):
# - Lane dim is the flat 24·24=576 conv pixel space — 4.5×128 exactly, so
#   VPU rows waste nothing (the staged kernels' (…,24,24) blocks pad lane
#   24→128, a 5.3× waste).
# - The input arrives pre-im2col'd in TAP-MAJOR layout (25, B, 576): each
#   tap read `x25_ref[t]` is a dense leading-dim slice (whole (Bb,576)
#   tiles), so the conv is 25 full-width FMAs per filter and the conv
#   weight grad is 25 multiply+sublane-reduce rows — no in-kernel reshapes,
#   which Mosaic would reject (lane-splitting). Measured: the batch-major
#   (Bb, 25, 576) alternative makes every tap read a strided mid-dim slice
#   and costs 30% end-to-end (940k → 1,218k img/s at Bb=64 on v5e).
# - The stride-4 "pool" is a dense (576, 36) matmul: Mp[uv, xy] =
#   w_s1[u−4x, v−4y] when (u,v) lies in window (x,y), else 0 — built ONCE
#   from iota masks at grid step 0 and reused (the TPU grid is sequential;
#   accumulator blocks persist in VMEM). Turning the sparse window scatter
#   into a small MXU matmul removes the pack/unpack relayouts entirely;
#   the transposed matmul is the backward scatter bp_output_c1.
# - Per-channel (Bb, 36) pool/FC rows tolerate lane padding (they are
#   ~0.4% of the VPU work).
# - True-scalar reductions (‖·‖₂ totals, bias grads, the 16 window-tap
#   sums) leave the kernel as small accumulator matrices and are finished
#   by O(model-size) XLA ops — Mosaic rejects scalar stores to VMEM.
# - Dots run Precision.DEFAULT, matching path A's on-chip precision (XLA
#   also runs DEFAULT): measured 13% faster than HIGHEST (6-pass f32
#   emulation) AND a TIGHTER on-chip diff vs path A (4e-4 vs 1.2e-3,
#   because both sides round the same way). CPU interpret-mode tests are
#   exact either way (no bf16 passes on CPU).


def _fused_kernel(
    x25_ref,      # (25, Bb, 576) im2col'd input block, tap-major
    y1h_ref,      # (Bb, 16) one-hot labels (10 real + 6 pad lanes)
    w_c1_ref,     # (6, 25)
    b_c1_ref,     # (6, 1)
    w_s1_ref,     # (16, 1) flat 4×4 pool kernel
    b_s1_ref,     # (1, 1)
    w_f_ref,      # (6, 36, 10) FC weight, channel-major split
    b_f_ref,      # (1, 10)
    # accumulator outputs (constant index map → persist across the grid)
    mp_ref,       # (576, 36) pool scatter matrix (built at step 0)
    err_ref,      # (1, 128) Σ per-sample ‖d_pre_f‖₂ (all lanes identical)
    gwf_ref,      # (6, 36, 10) Σ_b out_s1 ⊗ d_pre_f, channel-major
    gbf_ref,      # (1, 10) Σ_b d_pre_f
    cpool_ref,    # (576, 36) Σ_{b,m} out_c1 ⊗ d_pre_s1 (window-grad matrix)
    gbs1_ref,     # (1, 36) Σ_{b,m} d_pre_s1
    gwc1_ref,     # (150, 576) row m·25+t = Σ_b d_pre_c1[m] ⊙ x25[t]
    gbc1_ref,     # (6, 576) Σ_b d_pre_c1[m]
):
    f32 = err_ref.dtype

    @pl.when(pl.program_id(0) == 0)
    def _init():
        # Mp[uv, xy] = Σ_t w_s1[t] · [uv in window xy at tap t]: the pool's
        # scatter structure as data, so fwd/bwd pooling are MXU matmuls.
        uv = lax.broadcasted_iota(jnp.int32, (576, 36), 0)
        xy = lax.broadcasted_iota(jnp.int32, (576, 36), 1)
        di = uv // 24 - 4 * (xy // 6)
        dj = uv % 24 - 4 * (xy % 6)
        mp = jnp.zeros((576, 36), f32)
        for t in range(16):
            mp += jnp.where((di == t // 4) & (dj == t % 4), w_s1_ref[t, 0], 0.0)
        mp_ref[:] = mp
        err_ref[:] = jnp.zeros_like(err_ref)
        gwf_ref[:] = jnp.zeros_like(gwf_ref)
        gbf_ref[:] = jnp.zeros_like(gbf_ref)
        cpool_ref[:] = jnp.zeros_like(cpool_ref)
        gbs1_ref[:] = jnp.zeros_like(gbs1_ref)
        gwc1_ref[:] = jnp.zeros_like(gwc1_ref)
        gbc1_ref[:] = jnp.zeros_like(gbc1_ref)

    mp = mp_ref[:]
    dot = functools.partial(
        lax.dot_general,
        preferred_element_type=f32,
        precision=lax.Precision.DEFAULT,
    )

    # Forward: conv → pool (Mp matmul) → FC. Conv engine: one
    # (6,25)@(25,Bb,576) MXU dot when _MXU_CONV (r5 probe: 7× the VPU
    # loop, same operand layouts), else 25 tap-FMAs/filter on the VPU.
    bb = y1h_ref.shape[0]
    outs_c1 = []
    outs_s1 = []
    if _MXU_CONV:
        x25 = x25_ref[:]
        pre_c1 = dot(
            w_c1_ref[:].astype(x25.dtype), x25, (((1,), (0,)), ((), ()))
        )                                                       # (6, Bb, 576)
    pre_f = jnp.broadcast_to(b_f_ref[:], (bb, 10))
    for m in range(6):
        if _MXU_CONV:
            acc = pre_c1[m] + b_c1_ref[m, 0]
        else:
            acc = jnp.full((bb, 576), b_c1_ref[m, 0], f32)
            for t in range(25):
                acc += w_c1_ref[m, t] * x25_ref[t]
        out_m = _sigmoid(acc)                                   # (Bb, 576)
        outs_c1.append(out_m)
        pre_s1_m = dot(out_m, mp, (((1,), (0,)), ((), ()))) + b_s1_ref[0, 0]
        out_s1_m = _sigmoid(pre_s1_m)                           # (Bb, 36)
        outs_s1.append(out_s1_m)
        pre_f = pre_f + dot(out_s1_m, w_f_ref[m], (((1,), (0,)), ((), ())))
    out_f = _sigmoid(pre_f)

    # makeError + ‖·‖₂. Lane 10 of the one-hot block is the pad-sample mask
    # (1 for real rows, 0 for zero-padded rows): it zeroes d_pre_f, and with
    # it every grad and err contribution of the pad — so no grad masking is
    # needed anywhere downstream.
    mask = y1h_ref[:, 10:11]                                    # (Bb, 1)
    d_pre_f = (y1h_ref[:, :10] - out_f) * mask                  # (Bb, 10)
    # rank-2 throughout: Mosaic rejects rank-1 vector relayouts
    norms = jnp.sqrt(jnp.sum(d_pre_f * d_pre_f, axis=1, keepdims=True))
    err_ref[:] = err_ref[:] + jnp.sum(norms)

    # FC backward (≙ bp_weight_f/bp_bias_f/bp_output_s1, fused).
    gbf_ref[:] += jnp.sum(d_pre_f, axis=0, keepdims=True)
    for m in range(6):
        out_s1_m = outs_s1[m]
        gwf_ref[m] += dot(out_s1_m, d_pre_f, (((0,), (0,)), ((), ())))
        d_out_s1_m = dot(d_pre_f, w_f_ref[m], (((1,), (1,)), ((), ())))
        d_pre_s1_m = d_out_s1_m * out_s1_m * (1.0 - out_s1_m)   # (Bb, 36)
        gbs1_ref[:] += jnp.sum(d_pre_s1_m, axis=0, keepdims=True)
        out_m = outs_c1[m]
        # window-grad matrix: finished into g_w_s1 by XLA diagonal-einsum
        cpool_ref[:] += dot(out_m, d_pre_s1_m, (((0,), (0,)), ((), ())))
        # pool scatter-back + σ′ (≙ bp_output_c1 + bp_preact_c1)
        d_out_c1_m = dot(d_pre_s1_m, mp, (((1,), (1,)), ((), ())))
        d_pre_c1_m = d_out_c1_m * out_m * (1.0 - out_m)         # (Bb, 576)
        gbc1_ref[m : m + 1, :] += jnp.sum(d_pre_c1_m, axis=0, keepdims=True)
        # conv weight grad: 25 multiply+sublane-reduce rows per filter
        # (≙ bp_weight_c1's per-tap correlation, CUDA/layer.cu:307-335)
        for t in range(25):
            r = m * 25 + t
            gwc1_ref[r : r + 1, :] += jnp.sum(
                d_pre_c1_m * x25_ref[t], axis=0, keepdims=True
            )


FUSED_BLOCK = 128  # Mosaic's scoped-VMEM accounting charges the unrolled
                   # tap loops' temporaries (measured: 25.0 MB at Bb=64,
                   # 17.2 MB at Bb=32 against the DEFAULT 16 MB scoped
                   # limit) — so the call raises vmem_limit_bytes below;
                   # v5e VMEM is 128 MB. Fewer grid steps amortize the
                   # fixed per-step accumulator RMW work: same-session
                   # on-chip epoch sweep measured 1.349/1.403/1.388 M
                   # img/s at Bb=64/128/256 — 128 is the knee.
FUSED_VMEM_LIMIT = 100 * 1024 * 1024


def _fused_call(x25, y1h, params, n_pad: int):
    bb = _batch_block(n_pad, FUSED_BLOCK)
    f32 = jnp.float32
    outs = pl.pallas_call(
        _fused_kernel,
        grid=(n_pad // bb,),
        in_specs=[
            pl.BlockSpec((25, bb, 576), lambda g: (0, g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 16), lambda g: (g, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((6, 25), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((6, 1), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((16, 1), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((6, 36, 10), lambda g: (0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 10), lambda g: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((576, 36), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 128), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((6, 36, 10), lambda g: (0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 10), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((576, 36), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 36), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((150, 576), lambda g: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((6, 576), lambda g: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((576, 36), f32),   # Mp (scratch-as-output)
            jax.ShapeDtypeStruct((1, 128), f32),    # err
            jax.ShapeDtypeStruct((6, 36, 10), f32), # gwf
            jax.ShapeDtypeStruct((1, 10), f32),     # gbf
            jax.ShapeDtypeStruct((576, 36), f32),   # cpool
            jax.ShapeDtypeStruct((1, 36), f32),     # gbs1
            jax.ShapeDtypeStruct((150, 576), f32),  # gwc1 rows
            jax.ShapeDtypeStruct((6, 576), f32),    # gbc1 rows
        ],
        interpret=_interpret(),
        compiler_params=None if _interpret() else pltpu.CompilerParams(
            vmem_limit_bytes=FUSED_VMEM_LIMIT
        ),
    )(
        x25,
        y1h,
        params["c1"]["w"].reshape(6, 25).astype(f32),
        params["c1"]["b"].reshape(6, 1).astype(f32),
        params["s1"]["w"].reshape(16, 1).astype(f32),
        params["s1"]["b"].reshape(1, 1).astype(f32),
        params["f"]["w"].reshape(10, 6, 36).transpose(1, 2, 0).astype(f32),
        params["f"]["b"].reshape(1, 10).astype(f32),
    )
    return outs


def fused_value_and_ref_grads(
    params: Params, xs: jax.Array, ys: jax.Array
) -> Tuple[jax.Array, Params]:
    """(err_mean, batch-mean reference grads): the whole step's math in one
    Mosaic kernel + O(model-size) XLA finish ops.

    Differential contract: matches `staged_value_and_ref_grads` and path A
    (`jax.vmap(ops.reference.value_and_ref_grads)` + tree-mean) to fp
    tolerance — tests/test_ops_pallas.py, and on-chip in bench.py's
    `pallas_max_abs_diff` row.
    """
    n = xs.shape[0]
    f32 = jnp.float32
    pad = _pad_batch(n, min(n, FUSED_BLOCK))
    if pad:
        xs = jnp.concatenate([xs, jnp.zeros((pad,) + xs.shape[1:], xs.dtype)])
    n_pad = n + pad

    # Host-side prep (cheap XLA relayouts): im2col the input once, in the
    # TAP-MAJOR (25, B, 576) layout the kernel wants — tap t = 5p+q leads,
    # flat pixel uv on the lane dim.
    x25 = (
        lax.conv_general_dilated_patches(
            xs[:, None].astype(f32), (5, 5), (1, 1), "VALID"
        )
        .reshape(n_pad, 25, 576)
        .transpose(1, 0, 2)
    )
    if not _interpret() and not _FORCE_X25_F32:
        # STORE the dominant operand in bf16 (compute stays f32 — the
        # kernel's FMAs/dots promote on read). Zero numerics cost on the
        # chip: the patches conv above runs Precision.DEFAULT, whose MXU
        # passes already quantize values to bf16, so the bf16 store only
        # halves x25's HBM/VMEM traffic — measured ON-CHIP grad diff vs
        # the f32 store is exactly 0.0, and throughput goes 1.40M →
        # 1.93-3.59M img/s (+38% same-session; the higher reading is a
        # second session — relay variance, docs/bench_results.md).
        # Interpret mode (CPU tests) keeps exact f32: there
        # the patches op is exact, so a bf16 store WOULD change numerics.
        # DEPENDENCY: "zero cost" rests on an XLA lowering detail — if
        # patch extraction is ever lowered as pure data movement (no MXU
        # pass), this cast becomes a real precision loss. Guarded by the
        # TPU-gated regression test
        # tests/test_ops_pallas.py::test_fused_bf16_store_vs_f32_store.
        x25 = x25.astype(jnp.bfloat16)
    # One-hot labels padded to 16 lanes; lane 10 doubles as the pad-sample
    # mask (1 for real rows, 0 for pad rows — zeroing d_pre_f and with it
    # every grad & err contribution of the pad).
    y1h = jnp.zeros((n_pad, 16), f32)
    y1h = y1h.at[jnp.arange(n), ys].set(1.0, mode="drop")
    y1h = y1h.at[:n, 10].set(1.0)

    (mp, err, gwf, gbf, cpool, gbs1, gwc1, gbc1) = _fused_call(
        x25, y1h, params, n_pad
    )
    del mp  # Mp is kernel-internal state; outputs are the contract below

    inv_n = 1.0 / n
    err_mean = err[0, 0] * inv_n

    # XLA finish ops — each O(model size), no batch dimension left:
    # FC weight grad arrives channel-major transposed: (6, 36, 10) → (10, 216)
    g_w_f = gwf.transpose(2, 0, 1).reshape(10, 216) * inv_n
    g_b_f = gbf.reshape(10) * inv_n
    # g_w_s1[i,j] = Σ_{x,y} cpool[(4x+i)·24+4y+j, (x,y)]: diagonal einsum
    # over the window-grad matrix (repeated labels extract the diagonal).
    g_w_s1 = jnp.einsum("xiyjxy->ij", cpool.reshape(6, 4, 6, 4, 6, 6)) * inv_n
    g_b_s1 = jnp.sum(gbs1) / ref_ops.POOL_BIAS_NORM * inv_n
    g_w_c1 = (
        jnp.sum(gwc1, axis=1).reshape(6, 5, 5) / ref_ops.CONV_NORM * inv_n
    )
    g_b_c1 = jnp.sum(gbc1, axis=1) / ref_ops.CONV_NORM * inv_n

    grads: Params = {
        "c1": {"w": g_w_c1, "b": g_b_c1},
        "s1": {"w": g_w_s1, "b": g_b_s1},
        "f": {"w": g_w_f, "b": g_b_f},
    }
    return err_mean, grads


# The product fast path (--ops pallas, train/step.py, bench.py) is the
# fused megakernel; the staged per-op composition stays as the kernel
# library's differential anchor.
batched_value_and_ref_grads = fused_value_and_ref_grads
