"""Pallas conv kernels for the model zoo (BASELINE.json config #4:
"ResNet-18 on CIFAR-10 with Pallas conv kernels").

≙ the CUDA backend's hand-written conv kernels (CUDA/layer.cu:116-130)
generalized beyond the fixed LeNet shapes: a TPU-native conv as
**shift-and-matmul** — NHWC with channels on the lane axis, the conv's
9 (or 1) taps each ONE large MXU matmul over a row-shifted view of the
spatially-padded, flattened input:

    out_flat[r, :] = Σ_t  in_flat[r + off_t, :] @ W_t        (C × Cout)

where `in_flat` is (B·Hp·Wp, C) (Hp=H+2 zero-padded for 3×3 SAME) and
off_t = (dy−1)·Wp + (dx−1). Rows within `margin` of an image boundary
compute garbage that lands only on pad rows, which the wrapper slices
away — so every tap is a dense, unstrided slice + matmul, the shape
Mosaic and the MXU want (no im2col materialization, no gather).

The same kernel body serves all three conv derivatives:
- forward:  taps over x, weights W_t (C, Cout)
- dgrad:    taps over dout with NEGATED offsets, weights W_tᵀ (Cout, C)
- wgrad:    per-tap  x_shiftᵀ @ dout  (C, Cout), accumulated across the
            batch grid into a (T, C, Cout) block (≙ the CUDA atomicAdd
            weight-grad trees, without atomics: the TPU grid is
            sequential)

wired together with `jax.custom_vjp`, so `jax.grad` through the zoo
trainer uses Pallas for every conv FLOP.

Scope (documented, enforced): kernel 3×3 or 1×1, stride 1 or 2, SAME
padding, NHWC. Stride 2 computes the stride-1 output and subsamples —
~15% extra FLOPs on ResNet-18's three downsample convs, traded for one
kernel shape. Everything else falls back to XLA (`nn.layers.Conv2D`
keeps backend="xla" as default).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


# Shared with the LeNet kernel library: compile-vs-interpret keys off the
# axon-aware TPU detection, and batch blocks must divide the batch.
from parallel_cnn_tpu.ops.pallas import _batch_block, _interpret  # noqa: E402


# Scoped-VMEM model for choosing how many images ride one grid step.
# The block's true footprint is NOT just the double-buffered in/out
# pipeline buffers: Mosaic materializes each of the T unrolled tap slices
# (a (rows−2·margin, Cin) copy per tap) plus the f32 accumulator, and on
# v5e that stack is what OOMs first. The model below reproduces the
# compiler's own accounting to within ~1% (measured: the 8×8 256→512 3×3
# conv at bb=32 reports 71.59 MB scoped = 1.95 MB/img × 32 + the
# double-buffered 9.4 MB tap-weight block). Blocks are sized against a
# MODERATE budget, not the whole limit: measured on the chip, ResNet-18
# pallas-conv throughput is identical at bb=8 and bb=32 (6898 vs 6899
# img/s — the per-tap matmuls are already MXU-sized) while Mosaic compile
# time grows with block bytes, so big blocks only buy slower builds. The
# raised limit stays as safety margin over the model.
_VMEM_BUDGET = 32 * 1024 * 1024
_VMEM_LIMIT = 100 * 1024 * 1024


def _fwd_kernel(offsets, margin, x_ref, w_ref, o_ref):
    """o[r] = Σ_t x[r+off_t] @ w[t] for center rows; margin rows zeroed."""
    nb = o_ref.shape[0]
    lo, hi = margin, nb - margin
    acc = None
    for t, off in enumerate(offsets):
        part = lax.dot_general(
            x_ref[lo + off : hi + off, :],
            w_ref[t],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc = part if acc is None else acc + part
    o_ref[lo:hi, :] = acc.astype(o_ref.dtype)
    if margin:
        o_ref[:lo, :] = jnp.zeros((lo,) + o_ref.shape[1:], o_ref.dtype)
        o_ref[hi:, :] = jnp.zeros((nb - hi,) + o_ref.shape[1:], o_ref.dtype)


def _wgrad_kernel(offsets, margin, x_ref, g_ref, gw_ref):
    """gw[t] += x[center+off_t]ᵀ @ g[center], accumulated across the grid.

    Pad rows of g are zero (the wrapper embeds dout with zero pad), so
    their contributions vanish without masking.
    """
    @pl.when(pl.program_id(0) == 0)
    def _():
        gw_ref[:] = jnp.zeros_like(gw_ref)

    nb = g_ref.shape[0]
    lo, hi = margin, nb - margin
    g = g_ref[lo:hi, :]
    for t, off in enumerate(offsets):
        gw_ref[t] += lax.dot_general(
            x_ref[lo + off : hi + off, :],
            g,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(gw_ref.dtype)


def _tap_offsets(k: int, wp: int):
    if k == 1:
        return (0,), 0
    assert k == 3
    offs = tuple(
        (dy - 1) * wp + (dx - 1) for dy in range(3) for dx in range(3)
    )
    return offs, wp + 1  # margin ≥ max |offset|


def _pad_nhwc(x: jax.Array, k: int) -> jax.Array:
    if k == 1:
        return x
    return jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))


def _pick_bb(
    n: int, rows: int, cin: int, cout: int, taps: int, esz: int, w_esz: int
) -> int:
    # Bytes/image: double-buffered in+out pipeline blocks and T tap-slice
    # copies at the input element size (esz — bf16 halves them),
    # accumulator + per-tap dot result always f32. The (T, Cin, Cout)
    # block is batch-independent but double-buffered; its element size
    # differs per kernel — the fwd/dgrad tap-weight INPUT is at the input
    # dtype, the wgrad accumulator OUTPUT is always f32 (w_esz).
    per_img = rows * (esz * (2 * (cin + cout) + taps * cin) + 4 * 2 * cout)
    w_bytes = 2 * taps * cin * cout * w_esz
    avail = _VMEM_BUDGET - w_bytes
    return _batch_block(n, max(1, avail // per_img))


def _tapped_matmul(x_flat, w_taps, rows_per_img, offsets, margin, out_ch):
    """(B·rows, Cin) × (T, Cin, Cout) → (B·rows, Cout) over a batch grid."""
    n = x_flat.shape[0] // rows_per_img
    cin = x_flat.shape[1]
    esz = x_flat.dtype.itemsize
    bb = _pick_bb(n, rows_per_img, cin, out_ch, len(offsets), esz, esz)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, offsets, margin),
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec(
                (bb * rows_per_img, cin), lambda g: (g, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                w_taps.shape, lambda g: (0, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (bb * rows_per_img, out_ch), lambda g: (g, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((n * rows_per_img, out_ch), x_flat.dtype),
        interpret=_interpret(),
        compiler_params=None if _interpret() else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT
        ),
    )(x_flat, w_taps)


def _tapped_wgrad(x_flat, g_flat, rows_per_img, offsets, margin):
    n = x_flat.shape[0] // rows_per_img
    cin, cout = x_flat.shape[1], g_flat.shape[1]
    t = len(offsets)
    bb = _pick_bb(n, rows_per_img, cin, cout, t, x_flat.dtype.itemsize, 4)
    return pl.pallas_call(
        functools.partial(_wgrad_kernel, offsets, margin),
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec(
                (bb * rows_per_img, cin), lambda g: (g, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (bb * rows_per_img, cout), lambda g: (g, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (t, cin, cout), lambda g: (0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((t, cin, cout), jnp.float32),
        interpret=_interpret(),
        compiler_params=None if _interpret() else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT
        ),
    )(x_flat, g_flat)


def _conv_s1(x: jax.Array, w: jax.Array) -> jax.Array:
    """Stride-1 SAME conv, NHWC · HWIO → NHWC, k ∈ {1, 3}."""
    b, h, wd, cin = x.shape
    k = w.shape[0]
    cout = w.shape[3]
    xp = _pad_nhwc(x, k)
    hp, wp = xp.shape[1], xp.shape[2]
    offsets, margin = _tap_offsets(k, wp)
    x_flat = xp.reshape(b * hp * wp, cin)
    w_taps = w.reshape(k * k, cin, cout).astype(x.dtype)
    o_flat = _tapped_matmul(x_flat, w_taps, hp * wp, offsets, margin, cout)
    o = o_flat.reshape(b, hp, wp, cout)
    if k == 3:
        o = o[:, 1 : hp - 1, 1 : wp - 1, :]
    return o


def _s2_offsets(h: int, w: int, k: int) -> Tuple[int, int]:
    """Subsample phase matching XLA's SAME stride-2 window placement.

    XLA splits SAME padding as pad_lo = pad_total // 2; for k=3 an
    even-sized dim gets pad_total=1 → pad_lo=0, so output o is centered
    at 2o+1 — phase 1 of the (symmetrically padded) stride-1 output. Odd
    dims (and all k=1 cases) get phase 0.
    """
    if k == 1:
        return 0, 0
    return (1 if h % 2 == 0 else 0), (1 if w % 2 == 0 else 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def conv2d(x: jax.Array, w: jax.Array, stride: int = 1) -> jax.Array:
    """SAME conv via the Pallas tapped-matmul kernel; stride ∈ {1, 2}
    (stride 2 subsamples the stride-1 output at XLA's window phase)."""
    o = _conv_s1(x, w)
    if stride == 2:
        oy, ox = _s2_offsets(x.shape[1], x.shape[2], w.shape[0])
        o = o[:, oy::2, ox::2, :]
    return o


def _conv2d_fwd(x, w, stride):
    return conv2d(x, w, stride), (x, w)


def _conv2d_bwd(stride, res, g):
    x, w = res
    b, h, wd, cin = x.shape
    k = w.shape[0]
    cout = w.shape[3]
    if stride == 2:
        # scatter dout back onto the stride-1 grid at the forward's phase
        oy, ox = _s2_offsets(h, wd, k)
        gfull = jnp.zeros((b, h, wd, cout), g.dtype)
        g = gfull.at[:, oy::2, ox::2, :].set(g)
    # Shared padded-flat geometry for both grads; dout pad rows are ZERO,
    # so pad contributions vanish in each contraction.
    gp = _pad_nhwc(g, k)
    hp, wp = gp.shape[1], gp.shape[2]
    offsets, margin = _tap_offsets(k, wp)
    g_flat = gp.reshape(b * hp * wp, cout)

    # dgrad: dx[r] = Σ_t dout[r − off_t] @ w_tᵀ — same kernel, negated
    # offsets, transposed taps.
    wt = (
        w.reshape(k * k, cin, cout).transpose(0, 2, 1).astype(g.dtype)
    )  # (T, Cout, Cin)
    neg = tuple(-o for o in offsets)
    dx_flat = _tapped_matmul(g_flat, wt, hp * wp, neg, margin, cin)
    dx = dx_flat.reshape(b, hp, wp, cin)
    if k == 3:
        dx = dx[:, 1 : hp - 1, 1 : wp - 1, :]

    # wgrad: per-tap xᵀ @ dout accumulated over the batch grid.
    xp = _pad_nhwc(x, k)
    x_flat = xp.reshape(b * hp * wp, cin)
    gw = _tapped_wgrad(x_flat, g_flat, hp * wp, offsets, margin)
    return dx.astype(x.dtype), gw.reshape(k, k, cin, cout).astype(w.dtype)


conv2d.defvjp(_conv2d_fwd, _conv2d_bwd)


def supports(kernel: Tuple[int, int], strides: Tuple[int, int], padding: str) -> bool:
    """Shapes this kernel library covers; Conv2D falls back to XLA otherwise."""
    return (
        kernel in ((1, 1), (3, 3))
        and strides in ((1, 1), (2, 2))
        and padding == "SAME"
    )
