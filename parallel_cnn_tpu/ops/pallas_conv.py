"""Pallas conv kernels for the model zoo (BASELINE.json config #4:
"ResNet-18 on CIFAR-10 with Pallas conv kernels").

≙ the CUDA backend's hand-written conv kernels (CUDA/layer.cu:116-130)
generalized beyond the fixed LeNet shapes: a TPU-native conv as
**shift-and-matmul** — NHWC with channels on the lane axis, the conv's
taps each ONE large MXU matmul over a row-shifted view of the flattened
input:

    out_flat[r, :] = Σ_t  in_flat[r + off_t, :] @ W_t        (C × Cout)

Round-4 formulation (replaces round 3's full-perimeter-pad layout,
VERDICT r3 next #2 — the prior analysis lives in docs/future_work.md §1):

- **Pad H only.** The flat layout per image is ((T_top+H+T_bot)·W, C) —
  zero rows above/below sized by the tap reach, no W padding. Horizontal
  taps then wrap across row boundaries at the image edge; a per-tap
  COLUMN MASK (built in-kernel from a broadcasted_iota row index mod W —
  VPU-cheap) zeroes the wrapped lanes, which is exactly the SAME-padding
  semantics (the masked-out values are the zero pads). Row waste drops
  from (H+2)(W+2)/HW to (H+4)/H for 3×3 — 2.25× → 2.0× at 4×4,
  1.56× → 1.5× at 8×8 — and every tap slice stays dense.

- **Stride 2 computes ONLY the real output rows** via phase
  decomposition (was: stride-1 everything, then subsample — 4× waste on
  every downsample conv, ≈15% of ResNet-18 FLOPs and more of
  ResNet-50). For even H,W (every stride-2 conv in the ResNet
  families), split x into its 4 parity phases x_pq[i,j] = x[2i+p,2j+q];
  each tap (dy,dx) of a k-odd kernel then reads exactly one phase at a
  small dense offset:

      out[oy,ox] += W[dy,dx] · x_{(dy-pl)%2, (dx-pl)%2}[oy+a, ox+b]
      a = (dy-pl-(dy-pl)%2)/2,  b likewise,  pl = (k-2)//2 (XLA pad_lo)

  so the tapped-matmul kernel runs over ~(Hh+pad)·Wh rows per image —
  the true output size plus pad rows — instead of (H+pad)·(W+pad). The
  backward splits the same way: dgrad's four output phases each take
  the tap subset with matching parity (ONE kernel call, one pass over
  dout, four output refs), and wgrad contracts dout against the
  forward's phase tensors. Odd spatial dims (no zoo model hits them)
  fall back to stride-1 + phase-correct subsample for k=3.

- **k ∈ {1, 3, 5, 7}**: the tap geometry is computed, not hard-coded,
  so ResNet-50's 7×7-stride-2 stem runs on the same kernel family
  (taps' column masks generalize to multi-column shifts; pad rows size
  themselves from the tap reach).

The same generic kernel body serves all three conv derivatives:
- forward:  taps over x (1 ref) or its phases (4 refs), weights (C, Cout)
- dgrad:    taps over dout with negated/phase offsets, weights W_tᵀ
- wgrad:    per-tap  x_shiftᵀ @ dout  (C, Cout), accumulated across the
            batch grid into a (T, C, Cout) block (≙ the CUDA atomicAdd
            weight-grad trees, without atomics: the TPU grid is
            sequential)

wired together with `jax.custom_vjp`, so `jax.grad` through the zoo
trainer uses Pallas for every conv FLOP.

Scope (documented, enforced): odd kernel 1/3/5/7, stride 1 or 2, SAME
padding, NHWC; stride-2 for k>3 requires even spatial dims. Everything
else falls back to XLA (`nn.layers.Conv2D` keeps backend="xla" as
default).
"""

from __future__ import annotations

import functools
import logging
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


# Shared with the LeNet kernel library: compile-vs-interpret keys off the
# axon-aware TPU detection, and batch blocks must divide the batch.
from parallel_cnn_tpu.ops.pallas import _batch_block, _interpret  # noqa: E402

log = logging.getLogger(__name__)


# Scoped-VMEM model for choosing how many images ride one grid step.
# The block's true footprint is NOT just the double-buffered in/out
# pipeline buffers: Mosaic materializes each of the T unrolled tap slices
# (a (center-rows, Cin) copy per tap) plus the f32 accumulator, and on
# v5e that stack is what OOMs first. Blocks are sized against a MODERATE
# budget, not the whole limit: measured on the chip (round 3), ResNet-18
# pallas-conv throughput is identical at bb=8 and bb=32 (6898 vs 6899
# img/s — the per-tap matmuls are already MXU-sized) while Mosaic compile
# time grows with block bytes, so big blocks only buy slower builds. The
# raised limit stays as safety margin over the model.
_VMEM_BUDGET = 32 * 1024 * 1024
_VMEM_LIMIT = 100 * 1024 * 1024

# A tap: (input_ref_index, flat_row_offset, column_shift, weight_slot).
# column_shift is the tap's horizontal pixel shift: output rows whose
# pixel column j has j+shift outside [0, W) read a wrapped element and
# are masked to zero — the SAME-padding semantics.
Tap = Tuple[int, int, int, int]


def _col_masks(taps_per_out, w_col: int, lo: int, hi: int):
    """(rows, 1) validity masks keyed by column shift. Row index is
    block-local; every layout here has rows-per-image divisible by
    w_col and blocks start on image boundaries, so (row % w_col) IS the
    pixel column."""
    shifts = {s for taps in taps_per_out for (_, _, s, _) in taps if s}
    if not shifts:
        return {}
    col = lax.broadcasted_iota(jnp.int32, (hi - lo, 1), 0) + lo
    col = lax.rem(col, w_col)
    return {
        s: (col >= -s) & (col < w_col - s)
        for s in shifts
    }


def _plan_taps(entry):
    """Flatten a plan entry back to (ridx, off, shift, slot) tap views
    (slot unused) — lets _col_masks collect the shift set uniformly."""
    if entry[0] == "s":
        return [entry[1]]
    _, ridx, off1, s1, off2, s2, _pslot = entry
    return [(ridx, off1, s1, -1), (ridx, off2, s2, -1)]


def _build_plan(taps_per_out, w_stack, cout):
    """Greedily pair each output's taps (within a shared input ref) for
    the N-packing path when 2·cout fits the 128-lane tile; returns
    (plan_per_out, wp_stack or None). Odd taps stay single."""
    if cout > 64:
        return (
            [[("s", t) for t in taps] for taps in taps_per_out],
            None,
        )
    plans = []
    pair_ws = []
    for taps in taps_per_out:
        plan = []
        pending = {}
        for t in taps:
            r = t[0]
            if r in pending:
                t1 = pending.pop(r)
                pslot = len(pair_ws)
                pair_ws.append(
                    jnp.concatenate(
                        [w_stack[t1[3]], w_stack[t[3]]], axis=-1
                    )
                )
                plan.append(("p", r, t1[1], t1[2], t[1], t[2], pslot))
            else:
                pending[r] = t
        plan.extend(("s", t) for t in pending.values())
        plans.append(plan)
    if not pair_ws:
        return plans, None
    return plans, jnp.stack(pair_ws)


def _tap_kernel(plan_per_out, w_col, lo, tail, n_in, have_pairs, *refs):
    """Generic multi-ref, multi-output tapped matmul.

    refs = (x_ref_0..x_ref_{n_in-1}, w_ref[, wp_ref], o_ref_0..). Plan
    entries per output:
      ("s", (ridx, off, shift, slot))  —
        acc += mask ⊙ (x_refs[ridx][lo+off : hi+off] @ w_ref[slot])
      ("p", ridx, off1, s1, off2, s2, pslot)  —  N-PAIRED taps (r5,
        the MXU K=N=64 attack): two taps sharing an input ref compute as
        ONE dot against their weights stacked along N —
        big = x_refs[ridx][0:nb] @ wp_ref[pslot]        (nb, 2·cout)
        acc += mask1 ⊙ big[lo+off1 : hi+off1, :cout]
             + mask2 ⊙ big[lo+off2 : hi+off2, cout:]
        For cout ≤ 64 stages this doubles MXU lane fill (N 64 → 128) and
        halves the dot count; the row shifts move to the CONSUMING
        slices, which are free sublane slices. The 64-offset lane slice
        is validated on-chip (mosaic_probe pair-dot-laneslice, r5).
    Rows outside [lo, hi) are pad/garbage rows the wrappers slice away —
    they are left unwritten. hi = nb - tail keeps every tap slice inside
    the block, and pair dots read [0, nb) which covers every
    [lo+off, hi+off) by the same invariant.
    """
    x_refs = refs[:n_in]
    w_ref = refs[n_in]
    wp_ref = refs[n_in + 1] if have_pairs else None
    o_refs = refs[n_in + 1 + (1 if have_pairs else 0):]
    nb = o_refs[0].shape[0]
    lo_, hi = lo, nb - tail
    masks = _col_masks(
        [[t for e in plan for t in _plan_taps(e)] for plan in plan_per_out],
        w_col, lo_, hi,
    )
    for o_ref, plan in zip(o_refs, plan_per_out):
        cout = o_ref.shape[1]
        acc = None
        for entry in plan:
            if entry[0] == "s":
                ridx, off, shift, slot = entry[1]
                part = lax.dot_general(
                    x_refs[ridx][lo_ + off : hi + off, :],
                    w_ref[slot],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                if shift:
                    part = jnp.where(masks[shift], part, 0.0)
            else:
                _, ridx, off1, s1, off2, s2, pslot = entry
                big = lax.dot_general(
                    x_refs[ridx][:, :],
                    wp_ref[pslot],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                p1 = big[lo_ + off1 : hi + off1, :cout]
                if s1:
                    p1 = jnp.where(masks[s1], p1, 0.0)
                p2 = big[lo_ + off2 : hi + off2, cout:]
                if s2:
                    p2 = jnp.where(masks[s2], p2, 0.0)
                part = p1 + p2
            acc = part if acc is None else acc + part
        o_ref[lo_:hi, :] = acc.astype(o_ref.dtype)


def _wgrad_tap_kernel(taps, w_col, lo, tail, n_in, *refs):
    """gw[slot] += x_refs[ridx][center+off]ᵀ @ (mask ⊙ g[center]),
    accumulated across the sequential batch grid. g's pad rows are zero
    (the wrappers embed dout with zero pads), so only the column-wrap
    contributions need masking."""
    x_refs = refs[:n_in]
    g_ref = refs[n_in]
    gw_ref = refs[n_in + 1]

    @pl.when(pl.program_id(0) == 0)
    def _():
        gw_ref[:] = jnp.zeros_like(gw_ref)

    nb = g_ref.shape[0]
    lo_, hi = lo, nb - tail
    masks = _col_masks((taps,), w_col, lo_, hi)
    g = g_ref[lo_:hi, :]
    g_by_shift = {0: g}
    for s, m in masks.items():
        g_by_shift[s] = jnp.where(m, g, 0.0)
    for ridx, off, shift, slot in taps:
        gw_ref[slot] += lax.dot_general(
            x_refs[ridx][lo_ + off : hi + off, :],
            g_by_shift[shift],
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(gw_ref.dtype)


def _pick_bb(
    n: int,
    rows: int,
    cins: Sequence[int],
    tap_cins: Sequence[int],
    couts: Sequence[int],
    esz: int,
    out_esz: int,
    w_bytes: int,
    pair_temps: int = 0,
) -> int:
    """Images per grid step under the VMEM model: double-buffered in/out
    pipeline blocks, Mosaic's materialized per-tap slice copies (input
    dtype), f32 accumulator + per-tap dot result, minus the
    double-buffered weight block.

    Mosaic tiling constraint (r5 on-chip finding — interpret-mode tests
    can't catch it): a block's SUBLANE dim (bb·rows) must be a multiple
    of the dtype's sublane tile — 32/itemsize, i.e. 8 for f32, 16 for
    bf16 — unless the block spans the whole array (bb == n). With odd
    rows (e.g. ResNet-50's 224²-input deep blocks: 9·7 = 63 flat rows
    per image) a VMEM-picked bb of 4 yields a rejected 252-row block.
    The in- and out-blocks share the bb·rows sublane dim at their own
    dtypes, so the strictest (smallest-itemsize) tile governs. Pick the
    largest legal divisor under the VMEM target, else the smallest legal
    one above it (bb == n is always legal)."""
    cout = sum(couts)
    per_img = rows * (
        esz * (2 * sum(cins) + sum(tap_cins))
        + out_esz * 2 * cout
        + 4 * 2 * cout
        # N-pair packing (r5): each paired dot materializes a full-rows
        # (nb, 2·cout) f32 `big`; count every pair as simultaneously
        # live (conservative — Mosaic's scoped-stack accounting proved
        # 1.7MB tighter than the pre-pairing model at the stem shape).
        + 4 * 2 * max(couts, default=0) * pair_temps
    )
    avail = _VMEM_BUDGET - 2 * w_bytes
    want = max(1, avail // max(per_img, 1))
    tile = 32 // min(esz, out_esz)
    legal = [
        d for d in range(1, n + 1)
        if n % d == 0 and ((d * rows) % tile == 0 or d == n)
    ]
    below = [d for d in legal if d <= want]
    if below:
        return max(below)
    # No legal divisor fits the budget — the tiling constraint forces a
    # bigger block. Surface how far over the model says we land: over
    # budget is fine (the limit leaves headroom) but worth a debug trace;
    # over the hard limit predicts a Mosaic scoped-VMEM OOM.
    bb = min(legal)
    modeled = bb * per_img + 2 * w_bytes
    if modeled > _VMEM_LIMIT:
        log.warning(
            "pallas conv block bb=%d models %.1fMB VMEM, over the %.0fMB "
            "limit — expect a Mosaic OOM at this shape",
            bb, modeled / 2**20, _VMEM_LIMIT / 2**20,
        )
    elif modeled > _VMEM_BUDGET:
        log.debug(
            "pallas conv block bb=%d models %.1fMB VMEM, over the %.0fMB "
            "budget (tiling forced a larger-than-wanted block)",
            bb, modeled / 2**20, _VMEM_BUDGET / 2**20,
        )
    return bb


def _compiler_params():
    return None if _interpret() else pltpu.CompilerParams(
        vmem_limit_bytes=_VMEM_LIMIT
    )


def _tapped_matmul(
    x_flats: Sequence[jax.Array],
    w_stack: jax.Array,
    taps_per_out,
    rows_per_img: int,
    w_col: int,
    lo: int,
    tail: int,
    couts: Sequence[int],
    out_dtype,
) -> List[jax.Array]:
    """Run the generic forward/dgrad kernel over the batch grid."""
    n = x_flats[0].shape[0] // rows_per_img
    n_in = len(x_flats)
    cins = [x.shape[1] for x in x_flats]
    tap_cins = [
        cins[ridx] for taps in taps_per_out for (ridx, _, _, _) in taps
    ]
    esz = x_flats[0].dtype.itemsize
    # N-pair packing (r5): only when every output shares one cout ≤ 64 —
    # then two taps ride one K×128 dot (see _tap_kernel's plan docs).
    # Plan before picking bb: the pair temps count in the VMEM model.
    if len(set(couts)) == 1:
        plan_per_out, wp_stack = _build_plan(
            taps_per_out, w_stack, couts[0]
        )
    else:
        plan_per_out = [[("s", t) for t in taps] for taps in taps_per_out]
        wp_stack = None
    have_pairs = wp_stack is not None
    max_pairs = max(
        (sum(1 for e in plan if e[0] == "p") for plan in plan_per_out),
        default=0,
    )
    # Both weight stacks ride the grid double-buffered: the paired
    # (wp_stack) bytes count against VMEM exactly like the singles.
    w_bytes = w_stack.size * w_stack.dtype.itemsize
    if have_pairs:
        w_bytes += wp_stack.size * wp_stack.dtype.itemsize
    bb = _pick_bb(
        n, rows_per_img, cins, tap_cins, couts, esz,
        jnp.dtype(out_dtype).itemsize,
        w_bytes,
        pair_temps=max_pairs,
    )
    w_inputs = [w_stack] + ([wp_stack] if have_pairs else [])
    outs = pl.pallas_call(
        functools.partial(
            _tap_kernel, plan_per_out, w_col, lo, tail, n_in, have_pairs
        ),
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec(
                (bb * rows_per_img, c), lambda g: (g, 0),
                memory_space=pltpu.VMEM,
            )
            for c in cins
        ] + [
            pl.BlockSpec(w.shape, lambda g, nd=w.ndim: (0,) * nd,
                         memory_space=pltpu.VMEM)
            for w in w_inputs
        ],
        out_specs=[
            pl.BlockSpec(
                (bb * rows_per_img, c), lambda g: (g, 0),
                memory_space=pltpu.VMEM,
            )
            for c in couts
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n * rows_per_img, c), out_dtype)
            for c in couts
        ],
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(*x_flats, *w_inputs)
    return outs


def _tapped_wgrad(
    x_flats: Sequence[jax.Array],
    g_flat: jax.Array,
    taps,
    rows_per_img: int,
    w_col: int,
    lo: int,
    tail: int,
    n_slots: int,
) -> jax.Array:
    n = g_flat.shape[0] // rows_per_img
    n_in = len(x_flats)
    cins = [x.shape[1] for x in x_flats]
    cout = g_flat.shape[1]
    cin = cins[0]
    tap_cins = [cins[r] for (r, _, _, _) in taps]
    # VMEM model note: g appears in BOTH the input list (cins + [cout])
    # and the f32-accumulator term ([cout]) — in wgrad g is an input, so
    # the [cout] accumulator it models does not exist. The overcount is
    # intentional slack (picks a smaller bb than strictly needed, never a
    # too-large one); round-4 advisor finding, kept as-is by choice.
    bb = _pick_bb(
        n, rows_per_img, cins + [cout], tap_cins, [cout],
        x_flats[0].dtype.itemsize, 4,
        n_slots * cin * cout * 4,
    )
    return pl.pallas_call(
        functools.partial(_wgrad_tap_kernel, taps, w_col, lo, tail, n_in),
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec(
                (bb * rows_per_img, c), lambda g: (g, 0),
                memory_space=pltpu.VMEM,
            )
            for c in cins
        ] + [
            pl.BlockSpec(
                (bb * rows_per_img, cout), lambda g: (g, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (n_slots, cin, cout), lambda g: (0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((n_slots, cin, cout), jnp.float32),
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(*x_flats, g_flat)


# ---------------------------------------------------------------------------
# Tap geometry. All wrappers express their taps as (ref, a_off, b_off):
# a vertical pixel offset, a horizontal pixel offset, against a flat
# per-image layout of ((T_top + H + T_bot)·W) rows. _layout sizes the
# zero-pad rows from the tap reach so (a) every in-kernel slice stays
# inside the block and (b) semantically-zero reads (SAME padding rows)
# land on physical zero rows; column validity is the kernel's mask.
# ---------------------------------------------------------------------------


def _layout(h: int, w: int, flat_offs: Sequence[int]):
    """(rows_per_img, top_pad_rows, lo, tail) for a tap-offset set."""
    t_top = max(0, -(min(flat_offs) // w))  # ceil(-min/w) for min<0
    t_bot = max(0, -((-max(flat_offs)) // w))  # ceil(max/w)
    rows = (t_top + h + t_bot) * w
    return rows, t_top, t_top * w, t_bot * w


def _flatten_padded(x: jax.Array, t_top: int, t_bot: int) -> jax.Array:
    b, h, w, c = x.shape
    if t_top or t_bot:
        x = jnp.pad(x, ((0, 0), (t_top, t_bot), (0, 0), (0, 0)))
    return x.reshape(b * (h + t_top + t_bot) * w, c)


def _s1_taps(k: int, w: int):
    """Stride-1 tap set for odd k: (a_off, b_off) = (dy-p, dx-p)."""
    p = (k - 1) // 2
    return [
        (dy - p, dx - p, dy * k + dx) for dy in range(k) for dx in range(k)
    ]


def _s2_phase_taps(k: int, inverse: bool = False):
    """Stride-2 tap set (even dims): tap (dy,dx) → phase + offsets.

    XLA's SAME stride-2 placement for even dims puts pad_lo = (k-2)//2
    zero rows/cols before the image, i.e. out[o] is centered so the tap
    reads u = 2o + d - pad_lo. Phase = u parity; offset = (d-pl-phase)/2.
    `inverse` derives dgrad's mapping: output-phase p takes taps with
    d ≡ p + pl (mod 2) at offset -(…) — returned as (out_phase, a, b,
    slot) tuples instead.
    """
    pl_ = (k - 2) // 2
    taps = []
    for dy in range(k):
        for dx in range(k):
            slot = dy * k + dx
            if not inverse:
                py, ay = (dy - pl_) % 2, (dy - pl_ - (dy - pl_) % 2) // 2
                px, ax = (dx - pl_) % 2, (dx - pl_ - (dx - pl_) % 2) // 2
                taps.append((py * 2 + px, ay, ax, slot))
            else:
                # dx_phase (p,q) ← taps with dy ≡ p+pl, dx ≡ q+pl (mod 2)
                py = (dy + pl_) % 2
                px = (dx + pl_) % 2
                ay = -((dy - pl_ - ((dy - pl_) % 2)) // 2)
                ax = -((dx - pl_ - ((dx - pl_) % 2)) // 2)
                taps.append((py * 2 + px, ay, ax, slot))
    return taps


def _phases(x: jax.Array) -> List[jax.Array]:
    return [x[:, p::2, q::2, :] for p in (0, 1) for q in (0, 1)]


def _conv_s1(x: jax.Array, w: jax.Array) -> jax.Array:
    b, h, wd, cin = x.shape
    k, cout = w.shape[0], w.shape[3]
    taps_ab = _s1_taps(k, wd)
    flat_offs = [a * wd + bo for a, bo, _ in taps_ab]
    rows, t_top, lo, tail = _layout(h, wd, flat_offs)
    taps = tuple(
        (0, a * wd + bo, bo, slot) for (a, bo, slot) in taps_ab
    )
    (o_flat,) = _tapped_matmul(
        [_flatten_padded(x, t_top, (rows // wd) - h - t_top)],
        w.reshape(k * k, cin, cout).astype(x.dtype),
        (taps,), rows, wd, lo, tail, [cout], x.dtype,
    )
    return o_flat.reshape(b, rows // wd, wd, cout)[:, t_top : t_top + h]


def _dgrad_s1(g: jax.Array, w: jax.Array) -> jax.Array:
    """dx[a,b] = Σ_t W[dy,dx]·g[a−(dy−p), b−(dx−p)]: same kernel with
    negated offsets, transposed tap weights."""
    b, h, wd, cout = g.shape
    k, cin = w.shape[0], w.shape[2]
    taps_ab = [(-a, -bo, slot) for (a, bo, slot) in _s1_taps(k, wd)]
    flat_offs = [a * wd + bo for a, bo, _ in taps_ab]
    rows, t_top, lo, tail = _layout(h, wd, flat_offs)
    taps = tuple((0, a * wd + bo, bo, slot) for (a, bo, slot) in taps_ab)
    wt = w.reshape(k * k, cin, cout).transpose(0, 2, 1).astype(g.dtype)
    (dx_flat,) = _tapped_matmul(
        [_flatten_padded(g, t_top, (rows // wd) - h - t_top)],
        wt, (taps,), rows, wd, lo, tail, [cin], g.dtype,
    )
    return dx_flat.reshape(b, rows // wd, wd, cin)[:, t_top : t_top + h]


def _wgrad_s1(x: jax.Array, g: jax.Array, k: int) -> jax.Array:
    b, h, wd, cin = x.shape
    cout = g.shape[3]
    taps_ab = _s1_taps(k, wd)
    flat_offs = [a * wd + bo for a, bo, _ in taps_ab]
    rows, t_top, lo, tail = _layout(h, wd, flat_offs)
    taps = tuple((0, a * wd + bo, bo, slot) for (a, bo, slot) in taps_ab)
    t_bot = (rows // wd) - h - t_top
    gw = _tapped_wgrad(
        [_flatten_padded(x, t_top, t_bot)],
        _flatten_padded(g, t_top, t_bot),
        taps, rows, wd, lo, tail, k * k,
    )
    return gw.reshape(k, k, cin, cout)


def _conv_s2_even(x: jax.Array, w: jax.Array) -> jax.Array:
    b, h, wd, cin = x.shape
    k, cout = w.shape[0], w.shape[3]
    hh, wh = h // 2, wd // 2
    taps_pab = _s2_phase_taps(k)
    flat_offs = [a * wh + bo for _, a, bo, _ in taps_pab]
    rows, t_top, lo, tail = _layout(hh, wh, flat_offs)
    t_bot = (rows // wh) - hh - t_top
    taps = tuple(
        (ph, a * wh + bo, bo, slot) for (ph, a, bo, slot) in taps_pab
    )
    flats = [_flatten_padded(p, t_top, t_bot) for p in _phases(x)]
    (o_flat,) = _tapped_matmul(
        flats, w.reshape(k * k, cin, cout).astype(x.dtype), (taps,),
        rows, wh, lo, tail, [cout], x.dtype,
    )
    return o_flat.reshape(b, rows // wh, wh, cout)[:, t_top : t_top + hh]


def _dgrad_s2_even(g, w, h: int, wd: int) -> jax.Array:
    """The four dx phases each take the tap subset with matching parity:
    one kernel call, one pass over dout, four output refs."""
    b = g.shape[0]
    k, cin, cout = w.shape[0], w.shape[2], w.shape[3]
    hh, wh = h // 2, wd // 2
    inv = _s2_phase_taps(k, inverse=True)
    flat_offs = [a * wh + bo for _, a, bo, _ in inv]
    rows, t_top, lo, tail = _layout(hh, wh, flat_offs)
    t_bot = (rows // wh) - hh - t_top
    taps_per_out = tuple(
        tuple(
            (0, a * wh + bo, bo, slot)
            for (ph, a, bo, slot) in inv
            if ph == out_phase
        )
        for out_phase in range(4)
    )
    g_flat = _flatten_padded(g, t_top, t_bot)
    wt = w.reshape(k * k, cin, cout).transpose(0, 2, 1).astype(g.dtype)
    phase_outs = _tapped_matmul(
        [g_flat], wt, taps_per_out, rows, wh, lo, tail, [cin] * 4, g.dtype,
    )
    ps = [
        o.reshape(b, rows // wh, wh, cin)[:, t_top : t_top + hh]
        for o in phase_outs
    ]
    # Interleave phases back: columns then rows (pure XLA relayout).
    row0 = jnp.stack([ps[0], ps[1]], axis=3).reshape(b, hh, wd, cin)
    row1 = jnp.stack([ps[2], ps[3]], axis=3).reshape(b, hh, wd, cin)
    return jnp.stack([row0, row1], axis=2).reshape(b, h, wd, cin)


def _wgrad_s2_even(x: jax.Array, g: jax.Array, k: int) -> jax.Array:
    b, h, wd, cin = x.shape
    cout = g.shape[3]
    hh, wh = h // 2, wd // 2
    taps_pab = _s2_phase_taps(k)
    flat_offs = [a * wh + bo for _, a, bo, _ in taps_pab]
    rows, t_top, lo, tail = _layout(hh, wh, flat_offs)
    t_bot = (rows // wh) - hh - t_top
    taps = tuple(
        (ph, a * wh + bo, bo, slot) for (ph, a, bo, slot) in taps_pab
    )
    flats = [_flatten_padded(p, t_top, t_bot) for p in _phases(x)]
    gw = _tapped_wgrad(
        flats, _flatten_padded(g, t_top, t_bot), taps,
        rows, wh, lo, tail, k * k,
    )
    return gw.reshape(k, k, cin, cout)


# ---------------------------------------------------------------------------
# 1×1 convs: plain matmuls. Stride 2 subsamples FIRST (exact for SAME
# k=1 at any parity: out[o] = x[2o]), so no stride waste exists at all.
# ---------------------------------------------------------------------------


def _conv_1x1(x: jax.Array, w: jax.Array) -> jax.Array:
    b, h, wd, cin = x.shape
    cout = w.shape[3]
    (o_flat,) = _tapped_matmul(
        [x.reshape(b * h * wd, cin)],
        w.reshape(1, cin, cout).astype(x.dtype),
        (((0, 0, 0, 0),),),
        h * wd, wd, 0, 0, [cout], x.dtype,
    )
    return o_flat.reshape(b, h, wd, cout)


def _wgrad_1x1(x: jax.Array, g: jax.Array) -> jax.Array:
    b, h, wd, cin = x.shape
    cout = g.shape[3]
    gw = _tapped_wgrad(
        [x.reshape(b * h * wd, cin)],
        g.reshape(b * h * wd, cout),
        ((0, 0, 0, 0),),
        h * wd, wd, 0, 0, 1,
    )
    return gw.reshape(1, 1, cin, cout)


def _s2_offsets(h: int, w: int, k: int) -> Tuple[int, int]:
    """Subsample phase matching XLA's SAME stride-2 window placement.

    XLA splits SAME padding as pad_lo = pad_total // 2; for k=3 an
    even-sized dim gets pad_total=1 → pad_lo=0, so output o is centered
    at 2o+1 — phase 1 of the (symmetrically padded) stride-1 output. Odd
    dims (and all k=1 cases) get phase 0.
    """
    if k == 1:
        return 0, 0
    return (1 if h % 2 == 0 else 0), (1 if w % 2 == 0 else 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def conv2d(x: jax.Array, w: jax.Array, stride: int = 1) -> jax.Array:
    """SAME conv via the Pallas tapped-matmul kernels; stride ∈ {1, 2},
    odd k ∈ {1, 3, 5, 7}."""
    return _forward(x, w, stride)


def _forward(x, w, stride):
    k = w.shape[0]
    if k == 1:
        if stride == 2:
            x = x[:, ::2, ::2, :]
        return _conv_1x1(x, w)
    if stride == 1:
        return _conv_s1(x, w)
    if x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
        return _conv_s2_even(x, w)
    # Odd spatial dims at stride 2 (no zoo model hits this): stride-1 +
    # subsample at XLA's window phase. k-generic: for SAME padding with
    # odd k, pad_top(stride1) − pad_top(stride2) is 0 on odd dims and 1
    # on even dims for EVERY odd k ≥ 3 (pad_total is k−1 vs k−1 / k−2),
    # which is exactly _s2_offsets' per-dim formula — so the fallback
    # covers k ∈ {3, 5, 7} alike (closes the supports()/apply gap the
    # round-4 advisor flagged: supports() said yes for k>3 stride-2 but
    # this path raised on odd dims).
    o = _conv_s1(x, w)
    oy, ox = _s2_offsets(x.shape[1], x.shape[2], k)
    return o[:, oy::2, ox::2, :]


def _conv2d_fwd(x, w, stride):
    return _forward(x, w, stride), (x, w)


def _conv2d_bwd(stride, res, g):
    x, w = res
    b, h, wd, cin = x.shape
    k = w.shape[0]
    cout = w.shape[3]
    if k == 1:
        if stride == 2:
            xs = x[:, ::2, ::2, :]
            dxs = _conv_1x1(g, w.transpose(0, 1, 3, 2))
            dx = (
                jnp.zeros((b, h, wd, cin), x.dtype)
                .at[:, ::2, ::2, :]
                .set(dxs.astype(x.dtype))
            )
            gw = _wgrad_1x1(xs, g)
        else:
            dx = _conv_1x1(g, w.transpose(0, 1, 3, 2))
            gw = _wgrad_1x1(x, g)
        return dx.astype(x.dtype), gw.astype(w.dtype)
    if stride == 2 and h % 2 == 0 and wd % 2 == 0:
        dx = _dgrad_s2_even(g, w, h, wd)
        gw = _wgrad_s2_even(x, g, k)
        return dx.astype(x.dtype), gw.astype(w.dtype)
    if stride == 2:
        # Odd-dim fallback (k-generic): scatter dout onto the stride-1
        # grid at the forward's phase, then stride-1 grads.
        oy, ox = _s2_offsets(h, wd, k)
        gfull = jnp.zeros((b, h, wd, cout), g.dtype)
        g = gfull.at[:, oy::2, ox::2, :].set(g)
    dx = _dgrad_s1(g, w)
    gw = _wgrad_s1(x, g, k)
    return dx.astype(x.dtype), gw.astype(w.dtype)


conv2d.defvjp(_conv2d_fwd, _conv2d_bwd)


def supports(kernel: Tuple[int, int], strides: Tuple[int, int], padding: str) -> bool:
    """Shapes this kernel library covers; Conv2D falls back to XLA otherwise."""
    return (
        kernel in ((1, 1), (3, 3), (5, 5), (7, 7))
        and kernel[0] == kernel[1]
        and strides in ((1, 1), (2, 2))
        and padding == "SAME"
    )
