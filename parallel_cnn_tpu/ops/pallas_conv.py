"""Pallas conv kernels for the model zoo (BASELINE.json config #4:
"ResNet-18 on CIFAR-10 with Pallas conv kernels").

≙ the CUDA backend's hand-written conv kernels (CUDA/layer.cu:116-130)
generalized beyond the fixed LeNet shapes: a TPU-native conv as
**shift-and-matmul** — NHWC with channels on the lane axis, the conv's
taps each ONE large MXU matmul over a row-shifted view of the flattened
input:

    out_flat[r, :] = Σ_t  in_flat[r + off_t, :] @ W_t        (C × Cout)

Round-4 formulation (replaces round 3's full-perimeter-pad layout,
VERDICT r3 next #2 — the prior analysis lives in docs/future_work.md §1):

- **Pad H only.** The flat layout per image is ((T_top+H+T_bot)·W, C) —
  zero rows above/below sized by the tap reach, no W padding. Horizontal
  taps then wrap across row boundaries at the image edge; a per-tap
  COLUMN MASK (built in-kernel from a broadcasted_iota row index mod W —
  VPU-cheap) zeroes the wrapped lanes, which is exactly the SAME-padding
  semantics (the masked-out values are the zero pads). Row waste drops
  from (H+2)(W+2)/HW to (H+4)/H for 3×3 — 2.25× → 2.0× at 4×4,
  1.56× → 1.5× at 8×8 — and every tap slice stays dense.

- **Stride 2 computes ONLY the real output rows** via phase
  decomposition (was: stride-1 everything, then subsample — 4× waste on
  every downsample conv, ≈15% of ResNet-18 FLOPs and more of
  ResNet-50). For even H,W (every stride-2 conv in the ResNet
  families), split x into its 4 parity phases x_pq[i,j] = x[2i+p,2j+q];
  each tap (dy,dx) of a k-odd kernel then reads exactly one phase at a
  small dense offset:

      out[oy,ox] += W[dy,dx] · x_{(dy-pl)%2, (dx-pl)%2}[oy+a, ox+b]
      a = (dy-pl-(dy-pl)%2)/2,  b likewise,  pl = (k-2)//2 (XLA pad_lo)

  so the tapped-matmul kernel runs over ~(Hh+pad)·Wh rows per image —
  the true output size plus pad rows — instead of (H+pad)·(W+pad). The
  backward splits the same way: dgrad's four output phases each take
  the tap subset with matching parity (ONE kernel call, one pass over
  dout, four output refs), and wgrad contracts dout against the
  forward's phase tensors. Odd spatial dims (no zoo model hits them)
  fall back to stride-1 + phase-correct subsample for k=3.

- **k ∈ {1, 3, 5, 7}**: the tap geometry is computed, not hard-coded,
  so ResNet-50's 7×7-stride-2 stem runs on the same kernel family
  (taps' column masks generalize to multi-column shifts; pad rows size
  themselves from the tap reach).

The same generic kernel body serves all three conv derivatives:
- forward:  taps over x (1 ref) or its phases (4 refs), weights (C, Cout)
- dgrad:    taps over dout with negated/phase offsets, weights W_tᵀ
- wgrad:    per-tap  x_shiftᵀ @ dout  (C, Cout), accumulated across the
            batch grid into a (T, C, Cout) block (≙ the CUDA atomicAdd
            weight-grad trees, without atomics: the TPU grid is
            sequential)

wired together with `jax.custom_vjp`, so `jax.grad` through the zoo
trainer uses Pallas for every conv FLOP.

Round-6 additions (ISSUE 2, the round-5 verdict's perf mandate):

- **Fused epilogues** (≙ the reference CUDA kernels' fused
  bias+activation, CUDA/layer.cu:151-165): `conv2d_fused` applies
  per-channel scale+shift (folded inference-mode BN), an optional
  residual add, and ReLU on the f32 accumulator INSIDE the kernel's
  output block, before the single HBM write — one round-trip per layer
  tail instead of three-to-four. The VJP recomputes the cheap
  elementwise tail in XLA from the saved conv output (ReLU mask +
  residual pass-through) and routes the conv cotangent through the
  existing `_conv2d_bwd` kernels.

- **Double-buffered weight streaming**: when cout is large
  (multiple of `_COUT_TILE`) the weight stack no longer sits resident;
  a second, minor grid dimension walks cout tiles and Pallas's grid
  pipeline prefetches tile j+1's weight block while tile j multiplies.
  The x blocks keep a constant index along that dimension, so Mosaic
  skips their re-DMA. `_pick_bb` counts both in-flight weight buffers
  (the existing `2·w_bytes` term) against the per-tile bytes.

- **Row-band spatial tiling**: layouts whose per-image flat rows
  exceed `_MAX_ROWS_PER_IMG` (the 7×7-s2 stem at 224²: 49 taps ×
  12880 rows was Mosaic-compile-pathological, >25 min) are split into
  H-bands with a real-data halo; each band is its own kernel call and
  the results concatenate along H. Interior halo rows read true
  neighbor pixels, exterior ones the usual zero pads, so the math is
  exact — only compile-unit size changes.

Scope (documented, enforced): odd kernel 1/3/5/7, stride 1 or 2, SAME
padding, NHWC; stride-2 for k>3 requires even spatial dims. Everything
else falls back to XLA (`nn.layers.Conv2D` keeps backend="xla" as
default). `PCNN_PALLAS_STEM_XLA=1` additionally reroutes huge-input
k≥7 stems to XLA (`prefer_xla_fallback`) should a Mosaic regression
re-open the compile pathology that banding closes.
"""

from __future__ import annotations

import functools
import logging
import os
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


# Shared with the LeNet kernel library: compile-vs-interpret keys off the
# axon-aware TPU detection, and batch blocks must divide the batch.
from parallel_cnn_tpu.ops.pallas import _batch_block, _interpret  # noqa: E402

log = logging.getLogger(__name__)


# Scoped-VMEM model for choosing how many images ride one grid step.
# The block's true footprint is NOT just the double-buffered in/out
# pipeline buffers: Mosaic materializes each of the T unrolled tap slices
# (a (center-rows, Cin) copy per tap) plus the f32 accumulator, and on
# v5e that stack is what OOMs first. Blocks are sized against a MODERATE
# budget, not the whole limit: measured on the chip (round 3), ResNet-18
# pallas-conv throughput is identical at bb=8 and bb=32 (6898 vs 6899
# img/s — the per-tap matmuls are already MXU-sized) while Mosaic compile
# time grows with block bytes, so big blocks only buy slower builds. The
# raised limit stays as safety margin over the model.
_VMEM_BUDGET = 32 * 1024 * 1024
_VMEM_LIMIT = 100 * 1024 * 1024

# Weight-streaming tile (lanes): couts that are a strict multiple get a
# second grid dimension walking cout tiles — the grid pipeline then
# double-buffers the weight DMA (prefetch tile j+1 while j multiplies)
# instead of holding the whole stack resident. 0 disables.
_COUT_TILE = int(os.environ.get("PCNN_PALLAS_COUT_TILE", "256"))  # graftcheck: disable=env-outside-config -- import-time tiling knob read once into a module constant

# Row-band tiling threshold: per-image flat rows above this split into
# H-bands, each its own kernel call (Mosaic compile time scales with
# taps × rows; the 224² stem's 49 × 12880 was pathological). 6144 keeps
# every ≤64² zoo shape single-band.
_MAX_ROWS_PER_IMG = int(os.environ.get("PCNN_PALLAS_MAX_ROWS_PER_IMG",  # graftcheck: disable=env-outside-config -- import-time tiling knob read once into a module constant
                                       "6144"))

# Env-gated stem→XLA hybrid (see prefer_xla_fallback).
_STEM_XLA = os.environ.get("PCNN_PALLAS_STEM_XLA", "0") not in ("", "0")  # graftcheck: disable=env-outside-config -- import-time hybrid gate read once into a module constant


class Epilogue(NamedTuple):
    """Static spec for the in-kernel output-block epilogue.

    The kernel applies, on the f32 accumulator and in this order:
    ``z = acc·scale + shift``  (per-channel, folded inference-mode BN),
    ``z += residual``          (if ``residual``),
    ``z = max(z, 0)``          (if ``relu``),
    then writes ``z`` as the (only) y output. ``emit_preact`` adds a
    second output carrying the raw conv accumulator — the VJP's saved
    activation — at the cost of the extra HBM write, so the primal
    (inference) call never pays it."""

    relu: bool = True
    residual: bool = False
    emit_preact: bool = False

# A tap: (input_ref_index, flat_row_offset, column_shift, weight_slot).
# column_shift is the tap's horizontal pixel shift: output rows whose
# pixel column j has j+shift outside [0, W) read a wrapped element and
# are masked to zero — the SAME-padding semantics.
Tap = Tuple[int, int, int, int]


def _col_masks(taps_per_out, w_col: int, lo: int, hi: int):
    """(rows, 1) validity masks keyed by column shift. Row index is
    block-local; every layout here has rows-per-image divisible by
    w_col and blocks start on image boundaries, so (row % w_col) IS the
    pixel column."""
    shifts = {s for taps in taps_per_out for (_, _, s, _) in taps if s}
    if not shifts:
        return {}
    col = lax.broadcasted_iota(jnp.int32, (hi - lo, 1), 0) + lo
    col = lax.rem(col, w_col)
    return {
        s: (col >= -s) & (col < w_col - s)
        for s in shifts
    }


def _plan_taps(entry):
    """Flatten a plan entry back to (ridx, off, shift, slot) tap views
    (slot unused) — lets _col_masks collect the shift set uniformly."""
    if entry[0] == "s":
        return [entry[1]]
    _, ridx, off1, s1, off2, s2, _pslot = entry
    return [(ridx, off1, s1, -1), (ridx, off2, s2, -1)]


def _build_plan(taps_per_out, w_stack, cout):
    """Greedily pair each output's taps (within a shared input ref) for
    the N-packing path when 2·cout fits the 128-lane tile; returns
    (plan_per_out, wp_stack or None). Odd taps stay single."""
    if cout > 64:
        return (
            [[("s", t) for t in taps] for taps in taps_per_out],
            None,
        )
    plans = []
    pair_ws = []
    for taps in taps_per_out:
        plan = []
        pending = {}
        for t in taps:
            r = t[0]
            if r in pending:
                t1 = pending.pop(r)
                pslot = len(pair_ws)
                pair_ws.append(
                    jnp.concatenate(
                        [w_stack[t1[3]], w_stack[t[3]]], axis=-1
                    )
                )
                plan.append(("p", r, t1[1], t1[2], t[1], t[2], pslot))
            else:
                pending[r] = t
        plan.extend(("s", t) for t in pending.values())
        plans.append(plan)
    if not pair_ws:
        return plans, None
    return plans, jnp.stack(pair_ws)


def _tap_kernel(plan_per_out, w_col, lo, tail, n_in, have_pairs, ep, *refs):
    """Generic multi-ref, multi-output tapped matmul.

    refs = (x_ref_0..x_ref_{n_in-1}, w_ref[, wp_ref][, ss_ref][,
    res_ref], o_ref_0..). With an `ep: Epilogue`, ss_ref is an (8, cout)
    f32 block (row 0 scale, row 1 shift; 8 rows keep the f32 sublane
    tile legal when cout-tiling blocks it) and res_ref shares the output
    flat layout — its halo rows, like the output's, are never touched.
    Plan entries per output:
      ("s", (ridx, off, shift, slot))  —
        acc += mask ⊙ (x_refs[ridx][lo+off : hi+off] @ w_ref[slot])
      ("p", ridx, off1, s1, off2, s2, pslot)  —  N-PAIRED taps (r5,
        the MXU K=N=64 attack): two taps sharing an input ref compute as
        ONE dot against their weights stacked along N —
        big = x_refs[ridx][0:nb] @ wp_ref[pslot]        (nb, 2·cout)
        acc += mask1 ⊙ big[lo+off1 : hi+off1, :cout]
             + mask2 ⊙ big[lo+off2 : hi+off2, cout:]
        For cout ≤ 64 stages this doubles MXU lane fill (N 64 → 128) and
        halves the dot count; the row shifts move to the CONSUMING
        slices, which are free sublane slices. The 64-offset lane slice
        is validated on-chip (mosaic_probe pair-dot-laneslice, r5).
    Rows outside [lo, hi) are pad/garbage rows the wrappers slice away —
    they are left unwritten. hi = nb - tail keeps every tap slice inside
    the block, and pair dots read [0, nb) which covers every
    [lo+off, hi+off) by the same invariant.
    """
    x_refs = refs[:n_in]
    w_ref = refs[n_in]
    i = n_in + 1
    wp_ref = None
    if have_pairs:
        wp_ref = refs[i]
        i += 1
    ss_ref = res_ref = None
    if ep is not None:
        ss_ref = refs[i]
        i += 1
        if ep.residual:
            res_ref = refs[i]
            i += 1
    o_refs = refs[i:]
    nb = o_refs[0].shape[0]
    lo_, hi = lo, nb - tail
    masks = _col_masks(
        [[t for e in plan for t in _plan_taps(e)] for plan in plan_per_out],
        w_col, lo_, hi,
    )
    for o_ref, plan in zip(o_refs, plan_per_out):
        cout = o_ref.shape[1]
        acc = None
        for entry in plan:
            if entry[0] == "s":
                ridx, off, shift, slot = entry[1]
                part = lax.dot_general(
                    x_refs[ridx][lo_ + off : hi + off, :],
                    w_ref[slot],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                if shift:
                    part = jnp.where(masks[shift], part, 0.0)
            else:
                _, ridx, off1, s1, off2, s2, pslot = entry
                big = lax.dot_general(
                    x_refs[ridx][:, :],
                    wp_ref[pslot],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                p1 = big[lo_ + off1 : hi + off1, :cout]
                if s1:
                    p1 = jnp.where(masks[s1], p1, 0.0)
                p2 = big[lo_ + off2 : hi + off2, cout:]
                if s2:
                    p2 = jnp.where(masks[s2], p2, 0.0)
                part = p1 + p2
            acc = part if acc is None else acc + part
        if ep is None:
            o_ref[lo_:hi, :] = acc.astype(o_ref.dtype)
            continue
        # Fused epilogue, all on the f32 accumulator before the single
        # HBM write: (1, cout) × (rows, cout) broadcasts are the same
        # rank-2 VPU shape the column masks use (lane-major variant).
        z = acc * ss_ref[0:1, :] + ss_ref[1:2, :]
        if ep.residual:
            z = z + res_ref[lo_:hi, :].astype(jnp.float32)
        if ep.relu:
            z = jnp.maximum(z, 0.0)
        o_ref[lo_:hi, :] = z.astype(o_ref.dtype)
        if ep.emit_preact:
            o_refs[1][lo_:hi, :] = acc.astype(o_refs[1].dtype)


def _wgrad_tap_kernel(taps, w_col, lo, tail, n_in, *refs):
    """gw[slot] += x_refs[ridx][center+off]ᵀ @ (mask ⊙ g[center]),
    accumulated across the sequential batch grid. g's pad rows are zero
    (the wrappers embed dout with zero pads), so only the column-wrap
    contributions need masking."""
    x_refs = refs[:n_in]
    g_ref = refs[n_in]
    gw_ref = refs[n_in + 1]

    @pl.when(pl.program_id(0) == 0)
    def _():
        gw_ref[:] = jnp.zeros_like(gw_ref)

    nb = g_ref.shape[0]
    lo_, hi = lo, nb - tail
    masks = _col_masks((taps,), w_col, lo_, hi)
    g = g_ref[lo_:hi, :]
    g_by_shift = {0: g}
    for s, m in masks.items():
        g_by_shift[s] = jnp.where(m, g, 0.0)
    for ridx, off, shift, slot in taps:
        gw_ref[slot] += lax.dot_general(
            x_refs[ridx][lo_ + off : hi + off, :],
            g_by_shift[shift],
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(gw_ref.dtype)


# Observation hook for the static budget verifier (analysis/pallas_budget):
# when set, every block-size decision reports its VMEM model here, so
# `python -m parallel_cnn_tpu check` evaluates the same formula the
# kernels size with — no drift between the lint model and the runtime
# model is possible.  (tag, n, bb, per_img, w_bytes, modeled_bytes).
_budget_observer = None


def _vmem_per_img(
    rows: int,
    cins: Sequence[int],
    tap_cins: Sequence[int],
    couts: Sequence[int],
    esz: int,
    out_esz: int,
    pair_temps: int = 0,
) -> int:
    """Modeled VMEM bytes one image contributes to a pipeline block:
    double-buffered in/out blocks, Mosaic's materialized per-tap slice
    copies (input dtype), f32 accumulator + per-tap dot result, and the
    N-pair packing temporaries (see _pick_bb's docstring for the r5
    accounting notes)."""
    cout = sum(couts)
    return rows * (
        esz * (2 * sum(cins) + sum(tap_cins))
        + out_esz * 2 * cout
        + 4 * 2 * cout
        # N-pair packing (r5): each paired dot materializes a full-rows
        # (nb, 2·cout) f32 `big`; count every pair as simultaneously
        # live (conservative — Mosaic's scoped-stack accounting proved
        # 1.7MB tighter than the pre-pairing model at the stem shape).
        + 4 * 2 * max(couts, default=0) * pair_temps
    )


def _pick_bb(
    n: int,
    rows: int,
    cins: Sequence[int],
    tap_cins: Sequence[int],
    couts: Sequence[int],
    esz: int,
    out_esz: int,
    w_bytes: int,
    pair_temps: int = 0,
    tag: str = "conv",
) -> int:
    """Images per grid step under the VMEM model: double-buffered in/out
    pipeline blocks, Mosaic's materialized per-tap slice copies (input
    dtype), f32 accumulator + per-tap dot result, minus the
    double-buffered weight block. ``tag`` labels the over-budget logs —
    the fused-update kernels (ops/pallas_update.py) size their blocks
    through this same model (their momentum buffer rides in cins/couts,
    charged like any other double-buffered pipeline operand) and get the
    same warning/debug trail.

    Mosaic tiling constraint (r5 on-chip finding — interpret-mode tests
    can't catch it): a block's SUBLANE dim (bb·rows) must be a multiple
    of the dtype's sublane tile — 32/itemsize, i.e. 8 for f32, 16 for
    bf16 — unless the block spans the whole array (bb == n). With odd
    rows (e.g. ResNet-50's 224²-input deep blocks: 9·7 = 63 flat rows
    per image) a VMEM-picked bb of 4 yields a rejected 252-row block.
    The in- and out-blocks share the bb·rows sublane dim at their own
    dtypes, so the strictest (smallest-itemsize) tile governs. Pick the
    largest legal divisor under the VMEM target, else the smallest legal
    one above it (bb == n is always legal)."""
    per_img = _vmem_per_img(
        rows, cins, tap_cins, couts, esz, out_esz, pair_temps
    )
    avail = _VMEM_BUDGET - 2 * w_bytes
    want = max(1, avail // max(per_img, 1))
    tile = 32 // min(esz, out_esz)
    legal = [
        d for d in range(1, n + 1)
        if n % d == 0 and ((d * rows) % tile == 0 or d == n)
    ]
    below = [d for d in legal if d <= want]
    if below:
        bb = max(below)
        if _budget_observer is not None:
            _budget_observer(tag, n, bb, per_img, w_bytes,
                             bb * per_img + 2 * w_bytes)
        return bb
    # No legal divisor fits the budget — the tiling constraint forces a
    # bigger block. Surface how far over the model says we land: over
    # budget is fine (the limit leaves headroom) but worth a debug trace;
    # over the hard limit predicts a Mosaic scoped-VMEM OOM.
    bb = min(legal)
    modeled = bb * per_img + 2 * w_bytes
    if _budget_observer is not None:
        _budget_observer(tag, n, bb, per_img, w_bytes, modeled)
    if modeled > _VMEM_LIMIT:
        log.warning(
            "pallas %s block bb=%d models %.1fMB VMEM, over the %.0fMB "
            "limit — expect a Mosaic OOM at this shape",
            tag, bb, modeled / 2**20, _VMEM_LIMIT / 2**20,
        )
    elif modeled > _VMEM_BUDGET:
        log.debug(
            "pallas %s block bb=%d models %.1fMB VMEM, over the %.0fMB "
            "budget (tiling forced a larger-than-wanted block)",
            tag, bb, modeled / 2**20, _VMEM_BUDGET / 2**20,
        )
    return bb


def _compiler_params():
    return None if _interpret() else pltpu.CompilerParams(
        vmem_limit_bytes=_VMEM_LIMIT
    )


def _tapped_matmul(
    x_flats: Sequence[jax.Array],
    w_stack: jax.Array,
    taps_per_out,
    rows_per_img: int,
    w_col: int,
    lo: int,
    tail: int,
    couts: Sequence[int],
    out_dtype,
    *,
    epilogue: Optional[Epilogue] = None,
    ss: Optional[jax.Array] = None,
    res_flat: Optional[jax.Array] = None,
) -> List[jax.Array]:
    """Run the generic forward/dgrad kernel over the batch grid.

    With `epilogue`, `ss` is the (8, cout) f32 scale/shift block and
    `res_flat` (iff epilogue.residual) shares the OUTPUT flat layout;
    outputs become [y] or [y, preact].

    Weight streaming: when every output shares one cout that is a
    strict multiple of `_COUT_TILE` (and the N-pair path is off — that
    path only exists at cout ≤ 64), the grid gains a minor cout-tile
    dimension. The weight blocks walk tiles along it while the x-block
    index map stays constant, so Pallas's grid pipeline prefetches the
    NEXT weight tile during the current tile's dots and skips the x
    re-DMA — double-buffered weight streaming with no kernel-body
    change. `_pick_bb`'s `2·w_bytes` term then counts the two in-flight
    per-tile buffers instead of a resident full stack."""
    n = x_flats[0].shape[0] // rows_per_img
    n_in = len(x_flats)
    cins = [x.shape[1] for x in x_flats]
    tap_cins = [
        cins[ridx] for taps in taps_per_out for (ridx, _, _, _) in taps
    ]
    esz = x_flats[0].dtype.itemsize
    # N-pair packing (r5): only when every output shares one cout ≤ 64 —
    # then two taps ride one K×128 dot (see _tap_kernel's plan docs).
    # Plan before picking bb: the pair temps count in the VMEM model.
    if len(set(couts)) == 1:
        plan_per_out, wp_stack = _build_plan(
            taps_per_out, w_stack, couts[0]
        )
    else:
        plan_per_out = [[("s", t) for t in taps] for taps in taps_per_out]
        wp_stack = None
    have_pairs = wp_stack is not None
    max_pairs = max(
        (sum(1 for e in plan if e[0] == "p") for plan in plan_per_out),
        default=0,
    )
    cout0 = couts[0]
    tile_c = 0
    if (
        _COUT_TILE
        and not have_pairs
        and len(set(couts)) == 1
        and cout0 % _COUT_TILE == 0
        and cout0 > _COUT_TILE
        and w_stack.shape[-1] == cout0
    ):
        tile_c = _COUT_TILE
    lane = tile_c or cout0
    out_couts = list(couts)
    if epilogue is not None and epilogue.emit_preact:
        out_couts = out_couts + [cout0]
    # Both weight stacks ride the grid double-buffered: the paired
    # (wp_stack) bytes count against VMEM exactly like the singles.
    # Under cout tiling only one TILE's bytes is in flight (×2 buffers).
    w_bytes = w_stack.size * w_stack.dtype.itemsize
    if have_pairs:
        w_bytes += wp_stack.size * wp_stack.dtype.itemsize
    if tile_c:
        w_bytes = (w_bytes * tile_c) // cout0
    if epilogue is not None:
        w_bytes += 8 * lane * 4  # the (8, lane) f32 scale/shift block
    model_cins = list(cins)
    if res_flat is not None:
        model_cins.append(lane)  # residual rides the input pipeline
    bb = _pick_bb(
        n, rows_per_img, model_cins, tap_cins,
        [lane] * len(out_couts),
        esz,
        jnp.dtype(out_dtype).itemsize,
        w_bytes,
        pair_temps=max_pairs,
    )
    w_inputs = [w_stack] + ([wp_stack] if have_pairs else [])
    extras = []
    extra_specs = []
    if tile_c:
        nct = cout0 // tile_c
        grid = (n // bb, nct)  # minor dim last → weight tiles stream
        x_map = lambda g, j: (g, 0)  # noqa: E731 — constant along j
        out_map = lambda g, j: (g, j)  # noqa: E731
        w_specs = [
            pl.BlockSpec(
                w.shape[:-1] + (tile_c,),
                lambda g, j, nd=w.ndim: (0,) * (nd - 1) + (j,),
                memory_space=pltpu.VMEM,
            )
            for w in w_inputs
        ]
        ss_spec = pl.BlockSpec((8, tile_c), lambda g, j: (0, j),
                               memory_space=pltpu.VMEM)
    else:
        grid = (n // bb,)
        x_map = lambda g: (g, 0)  # noqa: E731
        out_map = lambda g: (g, 0)  # noqa: E731
        w_specs = [
            pl.BlockSpec(w.shape, lambda g, nd=w.ndim: (0,) * nd,
                         memory_space=pltpu.VMEM)
            for w in w_inputs
        ]
        ss_spec = pl.BlockSpec((8, cout0), lambda g: (0, 0),
                               memory_space=pltpu.VMEM)
    if epilogue is not None:
        extras.append(ss)
        extra_specs.append(ss_spec)
        if epilogue.residual:
            extras.append(res_flat)
            extra_specs.append(
                pl.BlockSpec((bb * rows_per_img, lane), out_map,
                             memory_space=pltpu.VMEM)
            )
    outs = pl.pallas_call(
        functools.partial(
            _tap_kernel, plan_per_out, w_col, lo, tail, n_in, have_pairs,
            epilogue,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (bb * rows_per_img, c), x_map,
                memory_space=pltpu.VMEM,
            )
            for c in cins
        ] + w_specs + extra_specs,
        out_specs=[
            pl.BlockSpec(
                (bb * rows_per_img, tile_c or c), out_map,
                memory_space=pltpu.VMEM,
            )
            for c in out_couts
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n * rows_per_img, c), out_dtype)
            for c in out_couts
        ],
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(*x_flats, *w_inputs, *extras)
    return outs


def _tapped_wgrad(
    x_flats: Sequence[jax.Array],
    g_flat: jax.Array,
    taps,
    rows_per_img: int,
    w_col: int,
    lo: int,
    tail: int,
    n_slots: int,
) -> jax.Array:
    n = g_flat.shape[0] // rows_per_img
    n_in = len(x_flats)
    cins = [x.shape[1] for x in x_flats]
    cout = g_flat.shape[1]
    cin = cins[0]
    tap_cins = [cins[r] for (r, _, _, _) in taps]
    # VMEM model note: g appears in BOTH the input list (cins + [cout])
    # and the f32-accumulator term ([cout]) — in wgrad g is an input, so
    # the [cout] accumulator it models does not exist. The overcount is
    # intentional slack (picks a smaller bb than strictly needed, never a
    # too-large one); round-4 advisor finding, kept as-is by choice.
    bb = _pick_bb(
        n, rows_per_img, cins + [cout], tap_cins, [cout],
        x_flats[0].dtype.itemsize, 4,
        n_slots * cin * cout * 4,
    )
    return pl.pallas_call(
        functools.partial(_wgrad_tap_kernel, taps, w_col, lo, tail, n_in),
        grid=(n // bb,),
        in_specs=[
            pl.BlockSpec(
                (bb * rows_per_img, c), lambda g: (g, 0),
                memory_space=pltpu.VMEM,
            )
            for c in cins
        ] + [
            pl.BlockSpec(
                (bb * rows_per_img, cout), lambda g: (g, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (n_slots, cin, cout), lambda g: (0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((n_slots, cin, cout), jnp.float32),
        interpret=_interpret(),
        compiler_params=_compiler_params(),
    )(*x_flats, g_flat)


# ---------------------------------------------------------------------------
# Tap geometry. All wrappers express their taps as (ref, a_off, b_off):
# a vertical pixel offset, a horizontal pixel offset, against a flat
# per-image layout of ((T_top + H + T_bot)·W) rows. _layout sizes the
# zero-pad rows from the tap reach so (a) every in-kernel slice stays
# inside the block and (b) semantically-zero reads (SAME padding rows)
# land on physical zero rows; column validity is the kernel's mask.
# ---------------------------------------------------------------------------


def _layout(h: int, w: int, flat_offs: Sequence[int]):
    """(rows_per_img, top_pad_rows, lo, tail) for a tap-offset set."""
    t_top = max(0, -(min(flat_offs) // w))  # ceil(-min/w) for min<0
    t_bot = max(0, -((-max(flat_offs)) // w))  # ceil(max/w)
    rows = (t_top + h + t_bot) * w
    return rows, t_top, t_top * w, t_bot * w


def _flatten_padded(x: jax.Array, t_top: int, t_bot: int) -> jax.Array:
    b, h, w, c = x.shape
    if t_top or t_bot:
        x = jnp.pad(x, ((0, 0), (t_top, t_bot), (0, 0), (0, 0)))
    return x.reshape(b * (h + t_top + t_bot) * w, c)


def _bands(h: int, rows_single: int, t_top: int, t_bot: int,
           w_col: int) -> List[Tuple[int, int]]:
    """Split output H-rows [0, h) into bands whose flat layouts stay
    under _MAX_ROWS_PER_IMG (Mosaic compile time scales with
    taps × rows — the 224² stem pathology). Bands are ceil-equal so at
    most two distinct kernel shapes compile."""
    if rows_single <= _MAX_ROWS_PER_IMG:
        return [(0, h)]
    cap_h = max(1, _MAX_ROWS_PER_IMG // w_col - t_top - t_bot)
    n_bands = -(-h // cap_h)
    hb = -(-h // n_bands)
    return [(r0, min(r0 + hb, h)) for r0 in range(0, h, hb)]


def _flatten_band(x: jax.Array, r0: int, r1: int, t_top: int,
                  t_bot: int) -> jax.Array:
    """Flat rows for output band [r0, r1): input H-rows
    [r0−t_top, r1+t_bot) with REAL interior halo rows and zero pads only
    outside the image — so for the full band (0, h) this IS
    _flatten_padded, and interior band edges read true neighbor pixels
    (exactness; column wrap stays the kernel mask's job)."""
    b, h, w, c = x.shape
    lo = r0 - t_top
    hi = r1 + t_bot
    pt, pb = max(0, -lo), max(0, hi - h)
    xs = x[:, max(lo, 0):min(hi, h)]
    if pt or pb:
        xs = jnp.pad(xs, ((0, 0), (pt, pb), (0, 0), (0, 0)))
    return xs.reshape(b * (hi - lo) * w, c)


def _banded_matmul(
    x_list: Sequence[jax.Array],
    w_stack: jax.Array,
    taps_per_out,
    h: int,
    wd: int,
    t_top: int,
    t_bot: int,
    couts: Sequence[int],
    out_dtype,
    *,
    epilogue: Optional[Epilogue] = None,
    ss: Optional[jax.Array] = None,
    res: Optional[jax.Array] = None,
) -> List[jax.Array]:
    """Run _tapped_matmul over row bands of the (phase-)images in
    x_list; returns per-output (b, h', wd, cout) arrays with the pad
    rows sliced away and bands concatenated along H."""
    b = x_list[0].shape[0]
    rows_single = (t_top + h + t_bot) * wd
    parts = []
    for r0, r1 in _bands(h, rows_single, t_top, t_bot, wd):
        hb = r1 - r0
        rows = (t_top + hb + t_bot) * wd
        outs = _tapped_matmul(
            [_flatten_band(x, r0, r1, t_top, t_bot) for x in x_list],
            w_stack, taps_per_out, rows, wd, t_top * wd, t_bot * wd,
            couts, out_dtype,
            epilogue=epilogue, ss=ss,
            res_flat=(
                None if res is None
                else _flatten_band(res, r0, r1, t_top, t_bot)
            ),
        )
        parts.append([
            o.reshape(b, rows // wd, wd, o.shape[1])[:, t_top:t_top + hb]
            for o in outs
        ])
    if len(parts) == 1:
        return parts[0]
    return [jnp.concatenate(ps, axis=1) for ps in zip(*parts)]


def _flatten_band_zero(x: jax.Array, r0: int, r1: int, t_top: int,
                       t_bot: int) -> jax.Array:
    """Band flattening with ZERO halo rows (vs _flatten_band's real
    ones): the cotangent side of banded wgrad. _wgrad_tap_kernel's
    center slice spans every image in a multi-image block, interior
    pad rows included — its correctness invariant is that g is zero
    there, which real-data halos would break (each band's weight-grad
    contribution is the sum over THAT band's output rows only)."""
    b, h, w, c = x.shape
    xs = x[:, r0:r1]
    if t_top or t_bot:
        xs = jnp.pad(xs, ((0, 0), (t_top, t_bot), (0, 0), (0, 0)))
    return xs.reshape(b * (r1 - r0 + t_top + t_bot) * w, c)


def _banded_wgrad(
    x_list: Sequence[jax.Array],
    g: jax.Array,
    taps,
    h: int,
    wd: int,
    t_top: int,
    t_bot: int,
    n_slots: int,
) -> jax.Array:
    """Per-band _tapped_wgrad calls summed in f32 — bands partition g's
    center rows exactly, so the per-band weight grads add. x bands carry
    real interior halos (the tap reads are data); g bands carry ZERO
    halos (the kernel's pad-rows-are-zero invariant)."""
    rows_single = (t_top + h + t_bot) * wd
    gw = None
    for r0, r1 in _bands(h, rows_single, t_top, t_bot, wd):
        hb = r1 - r0
        rows = (t_top + hb + t_bot) * wd
        part = _tapped_wgrad(
            [_flatten_band(x, r0, r1, t_top, t_bot) for x in x_list],
            _flatten_band_zero(g, r0, r1, t_top, t_bot),
            taps, rows, wd, t_top * wd, t_bot * wd, n_slots,
        )
        gw = part if gw is None else gw + part
    return gw


def _s1_taps(k: int, w: int):
    """Stride-1 tap set for odd k: (a_off, b_off) = (dy-p, dx-p)."""
    p = (k - 1) // 2
    return [
        (dy - p, dx - p, dy * k + dx) for dy in range(k) for dx in range(k)
    ]


def _s2_phase_taps(k: int, inverse: bool = False):
    """Stride-2 tap set (even dims): tap (dy,dx) → phase + offsets.

    XLA's SAME stride-2 placement for even dims puts pad_lo = (k-2)//2
    zero rows/cols before the image, i.e. out[o] is centered so the tap
    reads u = 2o + d - pad_lo. Phase = u parity; offset = (d-pl-phase)/2.
    `inverse` derives dgrad's mapping: output-phase p takes taps with
    d ≡ p + pl (mod 2) at offset -(…) — returned as (out_phase, a, b,
    slot) tuples instead.
    """
    pl_ = (k - 2) // 2
    taps = []
    for dy in range(k):
        for dx in range(k):
            slot = dy * k + dx
            if not inverse:
                py, ay = (dy - pl_) % 2, (dy - pl_ - (dy - pl_) % 2) // 2
                px, ax = (dx - pl_) % 2, (dx - pl_ - (dx - pl_) % 2) // 2
                taps.append((py * 2 + px, ay, ax, slot))
            else:
                # dx_phase (p,q) ← taps with dy ≡ p+pl, dx ≡ q+pl (mod 2)
                py = (dy + pl_) % 2
                px = (dx + pl_) % 2
                ay = -((dy - pl_ - ((dy - pl_) % 2)) // 2)
                ax = -((dx - pl_ - ((dx - pl_) % 2)) // 2)
                taps.append((py * 2 + px, ay, ax, slot))
    return taps


def _phases(x: jax.Array) -> List[jax.Array]:
    return [x[:, p::2, q::2, :] for p in (0, 1) for q in (0, 1)]


def _conv_s1(x: jax.Array, w: jax.Array, epilogue=None, ss=None,
             res=None) -> List[jax.Array]:
    b, h, wd, cin = x.shape
    k, cout = w.shape[0], w.shape[3]
    taps_ab = _s1_taps(k, wd)
    flat_offs = [a * wd + bo for a, bo, _ in taps_ab]
    _, t_top, _, tail = _layout(h, wd, flat_offs)
    taps = tuple(
        (0, a * wd + bo, bo, slot) for (a, bo, slot) in taps_ab
    )
    return _banded_matmul(
        [x], w.reshape(k * k, cin, cout).astype(x.dtype), (taps,),
        h, wd, t_top, tail // wd, [cout], x.dtype,
        epilogue=epilogue, ss=ss, res=res,
    )


def _dgrad_s1(g: jax.Array, w: jax.Array) -> jax.Array:
    """dx[a,b] = Σ_t W[dy,dx]·g[a−(dy−p), b−(dx−p)]: same kernel with
    negated offsets, transposed tap weights."""
    b, h, wd, cout = g.shape
    k, cin = w.shape[0], w.shape[2]
    taps_ab = [(-a, -bo, slot) for (a, bo, slot) in _s1_taps(k, wd)]
    flat_offs = [a * wd + bo for a, bo, _ in taps_ab]
    _, t_top, _, tail = _layout(h, wd, flat_offs)
    taps = tuple((0, a * wd + bo, bo, slot) for (a, bo, slot) in taps_ab)
    wt = w.reshape(k * k, cin, cout).transpose(0, 2, 1).astype(g.dtype)
    return _banded_matmul(
        [g], wt, (taps,), h, wd, t_top, tail // wd, [cin], g.dtype,
    )[0]


def _wgrad_s1(x: jax.Array, g: jax.Array, k: int) -> jax.Array:
    b, h, wd, cin = x.shape
    cout = g.shape[3]
    taps_ab = _s1_taps(k, wd)
    flat_offs = [a * wd + bo for a, bo, _ in taps_ab]
    _, t_top, _, tail = _layout(h, wd, flat_offs)
    taps = tuple((0, a * wd + bo, bo, slot) for (a, bo, slot) in taps_ab)
    gw = _banded_wgrad([x], g, taps, h, wd, t_top, tail // wd, k * k)
    return gw.reshape(k, k, cin, cout)


def _conv_s2_even(x: jax.Array, w: jax.Array, epilogue=None, ss=None,
                  res=None) -> List[jax.Array]:
    b, h, wd, cin = x.shape
    k, cout = w.shape[0], w.shape[3]
    hh, wh = h // 2, wd // 2
    taps_pab = _s2_phase_taps(k)
    flat_offs = [a * wh + bo for _, a, bo, _ in taps_pab]
    _, t_top, _, tail = _layout(hh, wh, flat_offs)
    taps = tuple(
        (ph, a * wh + bo, bo, slot) for (ph, a, bo, slot) in taps_pab
    )
    return _banded_matmul(
        _phases(x), w.reshape(k * k, cin, cout).astype(x.dtype), (taps,),
        hh, wh, t_top, tail // wh, [cout], x.dtype,
        epilogue=epilogue, ss=ss, res=res,
    )


def _dgrad_s2_even(g, w, h: int, wd: int) -> jax.Array:
    """The four dx phases each take the tap subset with matching parity:
    one kernel call, one pass over dout, four output refs."""
    b = g.shape[0]
    k, cin, cout = w.shape[0], w.shape[2], w.shape[3]
    hh, wh = h // 2, wd // 2
    inv = _s2_phase_taps(k, inverse=True)
    flat_offs = [a * wh + bo for _, a, bo, _ in inv]
    _, t_top, _, tail = _layout(hh, wh, flat_offs)
    taps_per_out = tuple(
        tuple(
            (0, a * wh + bo, bo, slot)
            for (ph, a, bo, slot) in inv
            if ph == out_phase
        )
        for out_phase in range(4)
    )
    wt = w.reshape(k * k, cin, cout).transpose(0, 2, 1).astype(g.dtype)
    ps = _banded_matmul(
        [g], wt, taps_per_out, hh, wh, t_top, tail // wh,
        [cin] * 4, g.dtype,
    )
    # Interleave phases back: columns then rows (pure XLA relayout).
    row0 = jnp.stack([ps[0], ps[1]], axis=3).reshape(b, hh, wd, cin)
    row1 = jnp.stack([ps[2], ps[3]], axis=3).reshape(b, hh, wd, cin)
    return jnp.stack([row0, row1], axis=2).reshape(b, h, wd, cin)


def _wgrad_s2_even(x: jax.Array, g: jax.Array, k: int) -> jax.Array:
    b, h, wd, cin = x.shape
    cout = g.shape[3]
    hh, wh = h // 2, wd // 2
    taps_pab = _s2_phase_taps(k)
    flat_offs = [a * wh + bo for _, a, bo, _ in taps_pab]
    _, t_top, _, tail = _layout(hh, wh, flat_offs)
    taps = tuple(
        (ph, a * wh + bo, bo, slot) for (ph, a, bo, slot) in taps_pab
    )
    gw = _banded_wgrad(
        _phases(x), g, taps, hh, wh, t_top, tail // wh, k * k,
    )
    return gw.reshape(k, k, cin, cout)


# ---------------------------------------------------------------------------
# 1×1 convs: plain matmuls. Stride 2 subsamples FIRST (exact for SAME
# k=1 at any parity: out[o] = x[2o]), so no stride waste exists at all.
# ---------------------------------------------------------------------------


def _conv_1x1(x: jax.Array, w: jax.Array, epilogue=None, ss=None,
              res=None) -> List[jax.Array]:
    b, h, wd, cin = x.shape
    cout = w.shape[3]
    return _banded_matmul(
        [x], w.reshape(1, cin, cout).astype(x.dtype),
        (((0, 0, 0, 0),),),
        h, wd, 0, 0, [cout], x.dtype,
        epilogue=epilogue, ss=ss, res=res,
    )


def _wgrad_1x1(x: jax.Array, g: jax.Array) -> jax.Array:
    b, h, wd, cin = x.shape
    cout = g.shape[3]
    gw = _banded_wgrad([x], g, ((0, 0, 0, 0),), h, wd, 0, 0, 1)
    return gw.reshape(1, 1, cin, cout)


def _s2_offsets(h: int, w: int, k: int) -> Tuple[int, int]:
    """Subsample phase matching XLA's SAME stride-2 window placement.

    XLA splits SAME padding as pad_lo = pad_total // 2; for k=3 an
    even-sized dim gets pad_total=1 → pad_lo=0, so output o is centered
    at 2o+1 — phase 1 of the (symmetrically padded) stride-1 output. Odd
    dims (and all k=1 cases) get phase 0.
    """
    if k == 1:
        return 0, 0
    return (1 if h % 2 == 0 else 0), (1 if w % 2 == 0 else 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def conv2d(x: jax.Array, w: jax.Array, stride: int = 1) -> jax.Array:
    """SAME conv via the Pallas tapped-matmul kernels; stride ∈ {1, 2},
    odd k ∈ {1, 3, 5, 7}."""
    return _forward(x, w, stride)


def _forward(x, w, stride):
    k = w.shape[0]
    if k == 1:
        if stride == 2:
            x = x[:, ::2, ::2, :]
        return _conv_1x1(x, w)[0]
    if stride == 1:
        return _conv_s1(x, w)[0]
    if x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
        return _conv_s2_even(x, w)[0]
    # Odd spatial dims at stride 2 (no zoo model hits this): stride-1 +
    # subsample at XLA's window phase. k-generic: for SAME padding with
    # odd k, pad_top(stride1) − pad_top(stride2) is 0 on odd dims and 1
    # on even dims for EVERY odd k ≥ 3 (pad_total is k−1 vs k−1 / k−2),
    # which is exactly _s2_offsets' per-dim formula — so the fallback
    # covers k ∈ {3, 5, 7} alike (closes the supports()/apply gap the
    # round-4 advisor flagged: supports() said yes for k>3 stride-2 but
    # this path raised on odd dims).
    o = _conv_s1(x, w)[0]
    oy, ox = _s2_offsets(x.shape[1], x.shape[2], k)
    return o[:, oy::2, ox::2, :]


def _conv2d_fwd(x, w, stride):
    return _forward(x, w, stride), (x, w)


def _conv2d_bwd(stride, res, g):
    x, w = res
    b, h, wd, cin = x.shape
    k = w.shape[0]
    cout = w.shape[3]
    if k == 1:
        if stride == 2:
            xs = x[:, ::2, ::2, :]
            dxs = _conv_1x1(g, w.transpose(0, 1, 3, 2))[0]
            dx = (
                jnp.zeros((b, h, wd, cin), x.dtype)
                .at[:, ::2, ::2, :]
                .set(dxs.astype(x.dtype))
            )
            gw = _wgrad_1x1(xs, g)
        else:
            dx = _conv_1x1(g, w.transpose(0, 1, 3, 2))[0]
            gw = _wgrad_1x1(x, g)
        return dx.astype(x.dtype), gw.astype(w.dtype)
    if stride == 2 and h % 2 == 0 and wd % 2 == 0:
        dx = _dgrad_s2_even(g, w, h, wd)
        gw = _wgrad_s2_even(x, g, k)
        return dx.astype(x.dtype), gw.astype(w.dtype)
    if stride == 2:
        # Odd-dim fallback (k-generic): scatter dout onto the stride-1
        # grid at the forward's phase, then stride-1 grads.
        oy, ox = _s2_offsets(h, wd, k)
        gfull = jnp.zeros((b, h, wd, cout), g.dtype)
        g = gfull.at[:, oy::2, ox::2, :].set(g)
    dx = _dgrad_s1(g, w)
    gw = _wgrad_s1(x, g, k)
    return dx.astype(x.dtype), gw.astype(w.dtype)


conv2d.defvjp(_conv2d_fwd, _conv2d_bwd)


# ---------------------------------------------------------------------------
# Fused conv epilogue (ISSUE 2 tentpole): relu?(conv·scale + shift
# [+ residual]) in ONE kernel pass — the elementwise tail rides the f32
# accumulator in VMEM instead of three-to-four extra HBM round-trips.
# ---------------------------------------------------------------------------


def _make_ss(scale: jax.Array, shift: jax.Array) -> jax.Array:
    """(8, cout) f32 scale/shift block: row 0 scale, row 1 shift. Eight
    rows keep the f32 sublane tile legal when cout-tiling blocks it."""
    cout = scale.shape[0]
    ss = jnp.zeros((8, cout), jnp.float32)
    return (
        ss.at[0].set(scale.astype(jnp.float32))
        .at[1].set(shift.astype(jnp.float32))
    )


def _fused_forward(x, w, scale, shift, residual, stride, relu,
                   want_preact):
    """Dispatch conv2d_fused over the same geometry split as _forward;
    returns (y, preact-or-None)."""
    k = w.shape[0]
    ep = Epilogue(
        relu=relu,
        residual=residual is not None,
        emit_preact=want_preact,
    )
    ss = _make_ss(scale, shift)
    if k == 1:
        xs = x[:, ::2, ::2, :] if stride == 2 else x
        outs = _conv_1x1(xs, w, epilogue=ep, ss=ss, res=residual)
    elif stride == 1:
        outs = _conv_s1(x, w, epilogue=ep, ss=ss, res=residual)
    elif x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
        outs = _conv_s2_even(x, w, epilogue=ep, ss=ss, res=residual)
    else:
        # Odd-dim stride-2 (outside every zoo model): conv in-kernel via
        # the stride-1 fallback, epilogue in XLA — still one conv pass.
        c = _forward(x, w, stride)
        z = c.astype(jnp.float32) * scale.astype(jnp.float32)
        z = z + shift.astype(jnp.float32)
        if residual is not None:
            z = z + residual.astype(jnp.float32)
        if relu:
            z = jnp.maximum(z, 0.0)
        return z.astype(x.dtype), (c if want_preact else None)
    if want_preact:
        return outs[0], outs[1]
    return outs[0], None


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def conv2d_fused(
    x: jax.Array,
    w: jax.Array,
    scale: jax.Array,
    shift: jax.Array,
    residual: Optional[jax.Array] = None,
    stride: int = 1,
    relu: bool = True,
) -> jax.Array:
    """``relu?(conv2d(x, w, stride)·scale + shift [+ residual])`` with
    the whole elementwise tail fused into the conv kernel's output
    block (≙ the reference CUDA kernels' fused bias+activation).

    scale/shift are per-channel f32 — fold inference-mode BN as
    ``scale = γ·rsqrt(var+ε)``, ``shift = β − mean·scale``. residual
    (optional) must have the conv OUTPUT shape. The primal pays exactly
    one HBM write; under `jax.grad` the fwd rule additionally saves the
    raw conv output so the bwd rule can rebuild the ReLU mask and route
    the conv cotangent through the existing `_conv2d_bwd` kernels, with
    residual grads passing straight through."""
    y, _ = _fused_forward(x, w, scale, shift, residual, stride, relu,
                          False)
    return y


def _conv2d_fused_fwd(x, w, scale, shift, residual, stride, relu):
    y, c = _fused_forward(x, w, scale, shift, residual, stride, relu,
                          True)
    return y, (x, w, scale, shift, residual, c)


def _conv2d_fused_bwd(stride, relu, saved, g):
    x, w, scale, shift, residual, c = saved
    cf = c.astype(jnp.float32)
    s = scale.astype(jnp.float32)
    z = cf * s + shift.astype(jnp.float32)
    if residual is not None:
        z = z + residual.astype(jnp.float32)
    gz = g.astype(jnp.float32)
    if relu:
        # where(z > 0): zero subgradient at z == 0, matching
        # jax.nn.relu's custom JVP (the unfused reference composition).
        gz = jnp.where(z > 0, gz, 0.0)
    d_shift = jnp.sum(gz, axis=(0, 1, 2)).astype(shift.dtype)
    d_scale = jnp.sum(gz * cf, axis=(0, 1, 2)).astype(scale.dtype)
    g_c = (gz * s).astype(x.dtype)
    dx, dw = _conv2d_bwd(stride, (x, w), g_c)
    d_res = None if residual is None else gz.astype(residual.dtype)
    return dx, dw, d_scale, d_shift, d_res


conv2d_fused.defvjp(_conv2d_fused_fwd, _conv2d_fused_bwd)


def supports(kernel: Tuple[int, int], strides: Tuple[int, int], padding: str) -> bool:
    """Shapes this kernel library covers; Conv2D falls back to XLA otherwise."""
    return (
        kernel in ((1, 1), (3, 3), (5, 5), (7, 7))
        and kernel[0] == kernel[1]
        and strides in ((1, 1), (2, 2))
        and padding == "SAME"
    )


def prefer_xla_fallback(kernel: Tuple[int, int],
                        strides: Tuple[int, int],
                        in_shape: Tuple[int, ...]) -> bool:
    """Honest compile-budget boundary for shapes `supports()` covers.

    Row-band tiling (`_bands`) brings the 7×7-s2 stem at 224² down from
    Mosaic-compile-pathological (>25 min single-unit) to a handful of
    ≤`_MAX_ROWS_PER_IMG` kernel units, so nothing is rerouted by
    default. `PCNN_PALLAS_STEM_XLA=1` is the documented stem→XLA hybrid
    escape hatch (docs/kernel_authoring.md): if a jaxlib/Mosaic
    regression re-opens the pathology, it reroutes ONLY the huge-input
    k≥7 stem conv while every residual block keeps the fused Pallas
    path."""
    if not _STEM_XLA:
        return False
    return (
        kernel[0] >= 7
        and strides[0] == 2
        and in_shape[1] * in_shape[2] >= 176 * 176
    )
