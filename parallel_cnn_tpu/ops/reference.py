"""Reference-semantics forward/backward ops (jax.numpy/lax path).

This module is the parity surface: it reproduces the numerics contract of
the reference's Sequential kernel library (SURVEY.md §2.1) exactly —
including the parts that are NOT the true gradient of any loss:

- the /576 normalization of the conv weight & bias grads
  (bp_weight_c1 / bp_bias_c1, Sequential/layer.h:381,389,402,412),
- the /216 normalization of the pool bias grad (bp_bias_s1, layer.h:304-316),
- unnormalized FC grads (bp_weight_f, layer.h:214-227),
- the (onehot − output) error vector used directly as d_preact of the final
  layer with no σ′ factor (makeError, layer.h:91-95).

Because of this, `jax.grad` of the forward pass would NOT reproduce the
reference training trajectory; the backward here is hand-written to spec
(SURVEY.md §7 "hard parts"), and exposed both as an explicit
`reference_grads` function and as a `custom_vjp` so the op library still
composes with JAX's functional transforms (vmap/jit/scan/shard_map).

All ops are single-sample (mirroring the per-sample reference kernels);
batching is `jax.vmap`, which XLA fuses into batched MXU convs — the
TPU-native replacement for the reference's 60k-iteration hot loop.

Shapes use channel-major layout like the reference:
    x: (28, 28) → c1: (6, 24, 24) → s1: (6, 6, 6) → f: (10,)
Weights: w_c1 (6, 5, 5), b_c1 (6,); w_s1 (4, 4), b_s1 (); w_f (10, 216),
b_f (10,).
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from parallel_cnn_tpu.ops.activations import (
    error_norm,
    make_error,
    sigmoid,
    sigmoid_grad_from_preact,
)

Params = Dict[str, Dict[str, jax.Array]]

CONV_NORM = 24.0 * 24.0  # `d` in bp_weight_c1/bp_bias_c1 (layer.h:381,402)
POOL_BIAS_NORM = 6.0 * 6.0 * 6.0  # `total_elements` in bp_bias_s1 (layer.h:304)


class Activations(NamedTuple):
    """Saved forward state — what the reference keeps in each Layer's
    output/preact buffers between forward_pass and back_pass."""

    x: jax.Array        # (28, 28)   l_input.output
    pre_c1: jax.Array   # (6, 24, 24) l_c1.preact
    out_c1: jax.Array   # (6, 24, 24) l_c1.output
    pre_s1: jax.Array   # (6, 6, 6)   l_s1.preact
    out_s1: jax.Array   # (6, 6, 6)   l_s1.output
    pre_f: jax.Array    # (10,)       l_f.preact
    out_f: jax.Array    # (10,)       l_f.output


# ---------------------------------------------------------------------------
# Forward kernels
# ---------------------------------------------------------------------------


def conv_c1_forward(x: jax.Array, w: jax.Array, b: jax.Array) -> jax.Array:
    """≙ fp_c1 (Sequential/layer.h:105-140): valid 5×5 conv, 6 filters,
    stride 1, + per-filter bias. (28,28)·(6,5,5) → (6,24,24).

    Expressed as `lax.conv_general_dilated` so XLA lowers it onto the MXU
    instead of the reference's 86k-MAC scalar loop nest.
    """
    # NCHW lhs (1,1,28,28), OIHW rhs (6,1,5,5) → (1,6,24,24)
    out = lax.conv_general_dilated(
        x[None, None, :, :],
        w[:, None, :, :],
        window_strides=(1, 1),
        padding="VALID",
    )
    return out[0] + b[:, None, None]


def pool_s1_forward(x: jax.Array, w: jax.Array, b: jax.Array) -> jax.Array:
    """≙ fp_s1 (Sequential/layer.h:143-181): the reference's nonstandard
    trainable "pooling" — ONE shared 4×4 kernel, stride 4, applied per
    feature map, + a single scalar bias. (6,24,24)·(4,4) → (6,6,6).

    A stride-4 window reshape + einsum: XLA turns this into one small
    contraction, no gather needed (windows tile exactly, 24 = 6·4).

    Generic over the channel count so the model-sharded path
    (parallel/intra_op.py) can call it on a channel shard.
    """
    xw = x.reshape(x.shape[0], 6, 4, 6, 4)  # [m, ox, i, oy, j] = x[m, 4ox+i, 4oy+j]
    return jnp.einsum("mxiyj,ij->mxy", xw, w) + b


def fc_forward(x: jax.Array, w: jax.Array, b: jax.Array) -> jax.Array:
    """≙ fp_preact_f + fp_bias_f (Sequential/layer.h:184-211):
    dense 216→10 dot products + bias."""
    return w @ x.reshape(-1) + b


def forward(params: Params, x: jax.Array) -> Activations:
    """≙ forward_pass (Sequential/Main.cpp:59-105): conv→σ→pool→σ→FC→σ,
    returning every preact/output buffer for the hand-written backward."""
    pre_c1 = conv_c1_forward(x, params["c1"]["w"], params["c1"]["b"])
    out_c1 = sigmoid(pre_c1)
    pre_s1 = pool_s1_forward(out_c1, params["s1"]["w"], params["s1"]["b"])
    out_s1 = sigmoid(pre_s1)
    pre_f = fc_forward(out_s1, params["f"]["w"], params["f"]["b"])
    out_f = sigmoid(pre_f)
    return Activations(x, pre_c1, out_c1, pre_s1, out_s1, pre_f, out_f)


def predict(params: Params, x: jax.Array) -> jax.Array:
    """≙ classify (Sequential/Main.cpp:186-200): argmax over the 10 outputs."""
    return jnp.argmax(forward(params, x).out_f)


# ---------------------------------------------------------------------------
# Backward kernels — hand-written to the reference contract
# ---------------------------------------------------------------------------


def backward(params: Params, acts: Activations, label: jax.Array) -> Tuple[jax.Array, Params]:
    """≙ makeError + back_pass (Sequential/Main.cpp:107-144,167).

    Returns `(err_norm, grads)` where `grads` is a params-shaped pytree g
    such that the reference's update is exactly `p += dt * g` for every
    weight AND bias. The reference updates biases *inside* the backward
    kernels (bp_bias_f layer.h:229-234, bp_bias_s1 :302-317, bp_bias_c1
    :398-414) with the same `+= dt * (normalized grad)` form — folding them
    into the grads pytree reproduces identical arithmetic while keeping the
    op functionally pure for jit/vmap/shard_map.
    """
    w_f, w_s1 = params["f"]["w"], params["s1"]["w"]

    # makeError (layer.h:91-95): d_preact_f = onehot(Y) − output
    d_pre_f = make_error(acts.out_f, label)
    err = error_norm(d_pre_f)  # vectorNorm (Main.cpp:28-34)

    # bp_weight_f (layer.h:214-227): outer product, unnormalized
    g_w_f = jnp.outer(d_pre_f, acts.out_s1.reshape(-1))
    # bp_bias_f (layer.h:229-234): bias += dt * d_preact  ⇒ g = d_preact
    g_b_f = d_pre_f

    # bp_output_s1 (layer.h:237-257): Wᵀ · d_preact_f
    d_out_s1 = (w_f.T @ d_pre_f).reshape(6, 6, 6)
    # bp_preact_s1 (layer.h:260-270): × σ′(preact)
    d_pre_s1 = d_out_s1 * sigmoid_grad_from_preact(acts.pre_s1)
    # bp_weight_s1 (layer.h:272-300): correlate d_preact with conv output
    # windows[m, x, i, y, j] = out_c1[m, 4x+i, 4y+j]
    out_c1_windows = acts.out_c1.reshape(6, 6, 4, 6, 4)
    g_w_s1 = jnp.einsum("mxy,mxiyj->ij", d_pre_s1, out_c1_windows)
    # bp_bias_s1 (layer.h:302-317): bias += dt * sum/216 ⇒ g = mean
    g_b_s1 = jnp.sum(d_pre_s1) / POOL_BIAS_NORM

    # bp_output_c1 (layer.h:319-346): scatter pool grads back through the
    # shared 4×4 kernel — an exact stride-4 "un-pool" since windows tile.
    d_out_c1 = jnp.einsum("mxy,ij->mxiyj", d_pre_s1, w_s1).reshape(6, 24, 24)
    # bp_preact_c1 (layer.h:348-369): × σ′(preact)
    d_pre_c1 = d_out_c1 * sigmoid_grad_from_preact(acts.pre_c1)
    # bp_weight_c1 (layer.h:371-395): /576-normalized correlation with input.
    # patches[p, x, y] = x[x+i, y+j] for p = 5*i+j
    patches = lax.conv_general_dilated_patches(
        acts.x[None, None, :, :], (5, 5), (1, 1), "VALID"
    )[0]  # (25, 24, 24)
    g_w_c1 = (
        jnp.einsum("mxy,pxy->mp", d_pre_c1, patches).reshape(6, 5, 5) / CONV_NORM
    )
    # bp_bias_c1 (layer.h:398-414): bias += dt * sum/576 ⇒ g = mean
    g_b_c1 = jnp.sum(d_pre_c1, axis=(1, 2)) / CONV_NORM

    grads: Params = {
        "c1": {"w": g_w_c1, "b": g_b_c1},
        "s1": {"w": g_w_s1, "b": g_b_s1},
        "f": {"w": g_w_f, "b": g_b_f},
    }
    return err, grads


def value_and_ref_grads(
    params: Params, x: jax.Array, label: jax.Array
) -> Tuple[jax.Array, Params]:
    """One sample's (err-norm, reference grads): forward + hand-written
    backward, the functional unit of the reference's per-sample loop
    (Sequential/Main.cpp:157-171)."""
    acts = forward(params, x)
    return backward(params, acts, label)


# ---------------------------------------------------------------------------
# custom_vjp wrapper — reference backward as a JAX-differentiable op
# ---------------------------------------------------------------------------


@jax.custom_vjp
def reference_loss(params: Params, x: jax.Array, label: jax.Array) -> jax.Array:
    """‖onehot(y) − f(x)‖₂ with a custom VJP that returns the REFERENCE
    grads (negated to match the descent convention of `jax.grad`).

    `-jax.grad(reference_loss)(params, x, y)` == `value_and_ref_grads(...)[1]`
    scaled by the incoming cotangent — so optax-style optimizers and the
    strict-parity trainer share one op. The true gradient of this norm is
    NOT what the reference computes (SURVEY.md §7); this VJP is the
    reference's backward by fiat.
    """
    acts = forward(params, x)
    return error_norm(make_error(acts.out_f, label))


def _ref_loss_fwd(params, x, label):
    acts = forward(params, x)
    err, grads = backward(params, acts, label)
    return err, (grads, x, label)


def _ref_loss_bwd(res, ct):
    grads, x, label = res
    # Descent convention: loss decreases along −g, and the reference applies
    # p += dt·g, so grad(loss) = −g (scaled by the cotangent).
    neg = jax.tree_util.tree_map(lambda g: -ct * g, grads)
    import numpy as np

    zero_label = np.zeros(label.shape, dtype=jax.dtypes.float0)
    return neg, jnp.zeros_like(x), zero_label


reference_loss.defvjp(_ref_loss_fwd, _ref_loss_bwd)
