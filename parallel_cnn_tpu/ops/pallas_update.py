"""Fused SGD/momentum update kernels over 1-D gradient buckets (round 7).

The unfused trainers end every step with a tree-wide optimizer pass: for
each leaf, read param + grad (+ momentum) from HBM, write param
(+ momentum) back — after the collective barrier, on the critical path.
These kernels collapse that pass to ONE fused elementwise kernel per
*bucket* (the same fixed-byte buckets parallel/collectives.py ships over
the ring), which is what makes *update-on-arrival* possible: the zoo's
explicit-collective step (train/zoo.py:make_fused_train_step) launches
bucket b's param+momentum update the moment its reduce-scatter sum is
final, overlapped with the other buckets' in-flight collectives, and
all-gathers already-updated parameter shards — no post-barrier optimizer
pass at all (the arXiv:1810.11112 schedule, extended from grads to the
update itself).

Math (per element, f32 throughout — master precision):

    fused_sgd:           p' = p − lr · (g · scale)
    fused_sgd_momentum:  m' = β·m + g · scale;   p' = p − lr · m'

which is exactly `optax.sgd(lr, momentum=β)` on grads pre-scaled by
``scale`` (the caller folds loss-scale × accumulation × device count into
one multiplier; tests/test_fused_step.py pins the bit-equality). ``scale``
is a *traced* scalar — the dynamic loss scale rides in it — passed as a
(1,1) block like the LeNet kernels' scalar operands; lr/β are static.

The LeNet engine's `p += dt·g` ascent convention is the same kernel with
``lr = −dt`` (train/step.py:fused_batched_step).

Buckets are 1-D; the wrappers pad to a lane multiple and present the
kernel a rank-2 (rows, 128) view — Mosaic-native tiling, no in-kernel
reshapes. Block row counts come from ops.pallas_conv._pick_bb so the
momentum buffer is charged in the same VMEM model (and trips the same
over-budget logs) as the conv pipeline operands.
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from parallel_cnn_tpu.ops.pallas import _interpret
from parallel_cnn_tpu.ops import pallas_conv
from parallel_cnn_tpu.parallel import collectives

_LANES = 128


def _sgd_kernel(p_ref, g_ref, s_ref, o_ref, *, lr):
    o_ref[:] = p_ref[:] - lr * (g_ref[:] * s_ref[0, 0])


def _sgd_momentum_kernel(p_ref, m_ref, g_ref, s_ref, po_ref, mo_ref, *,
                         lr, momentum):
    m = momentum * m_ref[:] + g_ref[:] * s_ref[0, 0]
    mo_ref[:] = m
    po_ref[:] = p_ref[:] - lr * m


def _pick_rows(n_rows: int, n_in: int, n_out: int) -> int:
    """Rows of 128 f32 lanes per grid step, via the conv VMEM model: each
    flat row is one 'image' of one row; every operand (params, grads, and
    — for the momentum variant — the momentum buffer, in AND out) is a
    double-buffered 128-lane pipeline block. Routing through _pick_bb is
    what charges the momentum buffer against the shared budget and emits
    the same over-budget warning/debug logs as the conv kernels."""
    return pallas_conv._pick_bb(
        n_rows, 1,
        cins=[_LANES] * n_in, tap_cins=[], couts=[_LANES] * n_out,
        esz=4, out_esz=4, w_bytes=0, tag="update",
    )


def _as_rows(x: jax.Array) -> Tuple[jax.Array, int]:
    """(rows, 128) zero-padded view of a 1-D f32 buffer + original length."""
    n = x.shape[0]
    rows = -(-n // _LANES)
    pad = rows * _LANES - n
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
    return x.reshape(rows, _LANES), n


def _scale_arr(scale) -> jax.Array:
    return jnp.asarray(scale, jnp.float32).reshape(1, 1)


def fused_sgd(p: jax.Array, g: jax.Array, *, lr: float,
              scale=1.0) -> jax.Array:
    """p − lr·(g·scale) for 1-D f32 buffers of equal length, one kernel."""
    if p.shape != g.shape or p.ndim != 1:
        raise ValueError(f"expected matching 1-D buffers, got {p.shape} "
                         f"vs {g.shape}")
    p2, n = _as_rows(p.astype(jnp.float32))
    g2, _ = _as_rows(g.astype(jnp.float32))
    rows = p2.shape[0]
    bb = _pick_rows(rows, n_in=2, n_out=1)
    row_spec = pl.BlockSpec((bb, _LANES), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_sgd_kernel, lr=float(lr)),
        grid=(rows // bb,),
        in_specs=[
            row_spec, row_spec,
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct(p2.shape, jnp.float32),
        compiler_params=pallas_conv._compiler_params(),
        interpret=_interpret(),
    )(p2, g2, _scale_arr(scale))
    return out.reshape(-1)[:n]


def fused_sgd_momentum(p: jax.Array, m: jax.Array, g: jax.Array, *,
                       lr: float, momentum: float,
                       scale=1.0) -> Tuple[jax.Array, jax.Array]:
    """(p', m') with m' = β·m + g·scale and p' = p − lr·m', one kernel."""
    if not (p.shape == m.shape == g.shape) or p.ndim != 1:
        raise ValueError(f"expected matching 1-D buffers, got {p.shape} / "
                         f"{m.shape} / {g.shape}")
    p2, n = _as_rows(p.astype(jnp.float32))
    m2, _ = _as_rows(m.astype(jnp.float32))
    g2, _ = _as_rows(g.astype(jnp.float32))
    rows = p2.shape[0]
    bb = _pick_rows(rows, n_in=3, n_out=2)
    row_spec = pl.BlockSpec((bb, _LANES), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    po, mo = pl.pallas_call(
        functools.partial(_sgd_momentum_kernel, lr=float(lr),
                          momentum=float(momentum)),
        grid=(rows // bb,),
        in_specs=[
            row_spec, row_spec, row_spec,
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(row_spec, row_spec),
        out_shape=(
            jax.ShapeDtypeStruct(p2.shape, jnp.float32),
            jax.ShapeDtypeStruct(p2.shape, jnp.float32),
        ),
        compiler_params=pallas_conv._compiler_params(),
        interpret=_interpret(),
    )(p2, m2, g2, _scale_arr(scale))
    return po.reshape(-1)[:n], mo.reshape(-1)[:n]


def tree_sgd(params, grads, *, lr: float, scale=1.0,
             bucket_bytes: int = collectives.DEFAULT_BUCKET_BYTES):
    """Tree-wide fused SGD through the bucket machinery: the pytree is
    packed into collectives.plan_buckets buckets (the exact flatten/
    unflatten round-trip), each bucket updated by ONE fused_sgd kernel.

    This is the single-device consumer of the bucket machinery — the
    LeNet engine's update (train/step.py:fused_batched_step; lr = −dt for
    the reference's p += dt·g convention). The zoo's distributed
    update-on-arrival path applies the same kernels per bucket *shard*
    inside its shard_map instead (train/zoo.py)."""
    plan = collectives.plan_buckets(params, bucket_bytes, shards=1)
    pb = collectives.flatten_buckets(params, plan)
    gb = collectives.flatten_buckets(grads, plan)
    out: List[jax.Array] = [
        fused_sgd(p, g, lr=lr, scale=scale) for p, g in zip(pb, gb)
    ]
    return collectives.unflatten_buckets(out, plan)
