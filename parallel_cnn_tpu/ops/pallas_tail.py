"""Fused loss-tail kernel: pool → flatten → FC → softmax-cross-entropy in
one pass, with a custom VJP whose forward emits ``dlogits`` directly
(round 7).

The unfused zoo tail materializes three intermediates to HBM between the
last conv block and the scalar loss: the pooled activations, the logits,
and the softmax probabilities (flatten is a free view). Backward then
re-reads them to form dlogits. This module collapses the whole tail into
ONE kernel per batch block: pooling, the FC contraction, and the
numerically-stable softmax-CE all run on the block's VMEM-resident f32
accumulator, and the kernel writes exactly two things — the per-sample
loss and ``dlogits = softmax(logits) − onehot`` — so backward starts from
dlogits with no softmax recompute and no intermediate round-trips.

Supported tail patterns (train/zoo.py routes through ``split_tail``):

- ``"max2"`` — MaxPool(2×2, stride 2, VALID) → Flatten → Dense: the CIFAR
  CNN head. The pool rides INTO the kernel via the 4-parity-phase trick
  (max of 4 elementwise phase views — no in-kernel strided windows), and
  the flatten→FC becomes a per-position tapped matmul
  ``Σ_p pooled_p @ w[p·C:(p+1)·C]`` (sublane slices only — no lane-merge
  reshape, which Mosaic forbids).
- ``"gap"``  — GlobalAvgPool → Dense: the ResNet/VGG head; the spatial
  mean accumulates in-kernel.
- ``"none"`` — Flatten → Dense on an already-flat input.

Backward (plain XLA on the residuals — the HBM win is the forward's):
``dW = pooledᵀ @ dl``, ``db = Σ dl``, ``dx = dl @ Wᵀ`` routed back
through the pool. The pooled activations are RECOMPUTED from the saved
primal input (cheap elementwise max / mean) rather than saved — the
standard recompute-in-backward trade that keeps the forward write-free.
Max-pool gradient routing matches XLA's select-and-scatter tie semantics
(first max in row-major window order wins) so the fused and unfused
steps track each other ≤1e-5 in f32 even through the ReLU-zero ties that
early training produces in half the windows.

Dispatch: the compiled Mosaic kernel runs on TPU; on CPU the SAME math
runs as an XLA composition inside the same custom_vjp (interpret-mode
Pallas would only add emulation overhead to identical semantics).
``PCNN_TAIL_KERNEL=1`` forces the kernel (the differential tests run it
in interpret mode against the XLA twin); ``=0`` forces the composition.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from parallel_cnn_tpu.ops.pallas import _batch_block, _interpret
from parallel_cnn_tpu.ops import pallas_conv

POOLS = ("max2", "gap", "none")

# Per-block VMEM target for the tail inputs (well under the conv model's
# 32MB budget — the tail's working set is small; this just caps the batch
# block for wide final feature maps like ResNet's 4×4×512).
_TAIL_BLOCK_BYTES = 8 * 1024 * 1024


class TailSplit(NamedTuple):
    """Where a Sequential's fused-able tail starts. ``trunk`` layers run
    unfused; layers[trunk:] are replaced by one fused_tail_loss call."""

    trunk: int
    pool: str


def split_tail(model) -> Optional[TailSplit]:
    """Recognize a supported tail suffix on a Sequential, else None (the
    caller degrades to the unfused composition)."""
    from parallel_cnn_tpu.nn import core, layers

    if not isinstance(model, core.Sequential):
        return None
    ls = list(model.layers)
    if (
        len(ls) >= 3
        and isinstance(ls[-3], layers.MaxPool)
        and ls[-3].window == (2, 2)
        and ls[-3].strides == (2, 2)
        and ls[-3].padding == "VALID"
        and isinstance(ls[-2], layers.Flatten)
        and isinstance(ls[-1], layers.Dense)
    ):
        return TailSplit(len(ls) - 3, "max2")
    if (
        len(ls) >= 2
        and isinstance(ls[-2], layers.GlobalAvgPool)
        and isinstance(ls[-1], layers.Dense)
    ):
        return TailSplit(len(ls) - 2, "gap")
    if (
        len(ls) >= 2
        and isinstance(ls[-2], layers.Flatten)
        and isinstance(ls[-1], layers.Dense)
    ):
        return TailSplit(len(ls) - 2, "none")
    return None


def _use_kernel() -> bool:
    env = os.environ.get("PCNN_TAIL_KERNEL")  # graftcheck: disable=env-outside-config -- call-time toggle so tests and the budget analyzer can force the kernel leg per-trace
    if env is not None:
        return env != "0"
    return not _interpret()


def _phases(x):
    """The 4 parity-phase views of an even-H/W NHWC tensor, in row-major
    window order — max-pool(2,2,stride 2) is their elementwise max."""
    return (
        x[:, 0::2, 0::2, :],
        x[:, 0::2, 1::2, :],
        x[:, 1::2, 0::2, :],
        x[:, 1::2, 1::2, :],
    )


def _pooled_flat(x, pool):
    """(pooled activations as (B, D), D) for the FC contraction."""
    if pool == "max2":
        p0, p1, p2, p3 = _phases(x)
        pooled = jnp.maximum(jnp.maximum(p0, p1), jnp.maximum(p2, p3))
        return pooled.reshape(pooled.shape[0], -1), pooled
    if pool == "gap":
        pooled = jnp.mean(x, axis=(1, 2))
        return pooled, pooled
    return x.reshape(x.shape[0], -1), None


def _ce_from_logits(logits32, oh):
    """(per-sample loss, dlogits) from f32 logits — the shared math both
    the kernel and the XLA composition implement."""
    m = jnp.max(logits32, axis=-1, keepdims=True)
    e = jnp.exp(logits32 - m)
    se = jnp.sum(e, axis=-1, keepdims=True)
    loss_i = (jnp.log(se) + m)[:, 0] - jnp.sum(logits32 * oh, axis=-1)
    return loss_i, e / se - oh


# --------------------------------------------------------------------------
# Kernel forward (TPU; interpret mode under PCNN_TAIL_KERNEL=1 on CPU)
# --------------------------------------------------------------------------


def _tail_kernel(*refs, pool, P, C):
    """One batch block: pool → tapped FC → softmax-CE → (loss_i, dlogits).

    Inputs (per pool mode):
      max2: ph00, ph01, ph10, ph11 (bb, P, C) — the parity phase views
      gap:  xs (bb, P, C) with P = H·W spatial positions
      none: xf (bb, D)
    then w (D|C, K), b (1, K), oh (bb, K); outputs loss (bb, 1), dl (bb, K).
    """
    if pool == "max2":
        p00, p01, p10, p11, w_ref, b_ref, oh_ref, loss_ref, dl_ref = refs
    else:
        x_ref, w_ref, b_ref, oh_ref, loss_ref, dl_ref = refs
    acc = b_ref[...].astype(jnp.float32)  # (1, K), broadcasts over bb
    if pool == "max2":
        for p in range(P):
            pooled_p = jnp.maximum(
                jnp.maximum(p00[:, p, :], p01[:, p, :]),
                jnp.maximum(p10[:, p, :], p11[:, p, :]),
            )
            acc = acc + jnp.dot(
                pooled_p, w_ref[p * C:(p + 1) * C, :],
                preferred_element_type=jnp.float32,
            )
    elif pool == "gap":
        mean = x_ref[:, 0, :].astype(jnp.float32)
        for p in range(1, P):
            mean = mean + x_ref[:, p, :].astype(jnp.float32)
        mean = (mean * (1.0 / P)).astype(x_ref.dtype)
        acc = acc + jnp.dot(mean, w_ref[...],
                            preferred_element_type=jnp.float32)
    else:
        acc = acc + jnp.dot(x_ref[...], w_ref[...],
                            preferred_element_type=jnp.float32)
    oh = oh_ref[...].astype(jnp.float32)
    loss_i, dl = _ce_from_logits(acc, oh)
    loss_ref[...] = loss_i[:, None]
    dl_ref[...] = dl


def _kernel_forward(x, w, b, oh, pool):
    B, K = oh.shape
    if pool == "max2":
        phs = [p.reshape(B, -1, p.shape[-1]) for p in _phases(x)]
        P, C = phs[0].shape[1], phs[0].shape[2]
        per_img = 4 * P * C * x.dtype.itemsize
        ins = phs
    elif pool == "gap":
        xs = x.reshape(B, -1, x.shape[-1])
        P, C = xs.shape[1], xs.shape[2]
        per_img = P * C * x.dtype.itemsize
        ins = [xs]
    else:
        xf = x.reshape(B, -1)
        P, C = 1, xf.shape[1]
        per_img = C * x.dtype.itemsize
        ins = [xf]
    bb = _batch_block(B, max(1, min(128, _TAIL_BLOCK_BYTES // max(per_img, 1))))
    if pallas_conv._budget_observer is not None:
        # Same shape of report as _pick_bb: double-buffered input blocks,
        # whole-weight residency, double-buffered oh/loss/dl blocks.
        w_bytes = w.size * w.dtype.itemsize + K * 4
        modeled = (
            2 * bb * per_img + 2 * w_bytes
            + 2 * bb * K * oh.dtype.itemsize          # one-hot block
            + 2 * bb * (K + 1) * 4                    # dl + loss outputs
        )
        pallas_conv._budget_observer(
            f"tail/{pool}", B, bb, per_img, w_bytes, modeled
        )
    if pool == "none":
        in_specs = [pl.BlockSpec((bb, C), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)]
    else:
        in_specs = [
            pl.BlockSpec((bb, P, C), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM)
            for _ in ins
        ]
    in_specs += [
        pl.BlockSpec(w.shape, lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, K), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((bb, K), lambda i: (i, 0), memory_space=pltpu.VMEM),
    ]
    loss_i, dl = pl.pallas_call(
        functools.partial(_tail_kernel, pool=pool, P=P, C=C),
        grid=(B // bb,),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((bb, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, K), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, K), jnp.float32),
        ),
        compiler_params=pallas_conv._compiler_params(),
        interpret=_interpret(),
    )(*ins, w, b.reshape(1, K), oh)
    return loss_i[:, 0], dl


# --------------------------------------------------------------------------
# custom_vjp wiring (one cached closure per pool mode)
# --------------------------------------------------------------------------


def _forward(x, w, b, oh, pool):
    if _use_kernel():
        loss_i, dl = _kernel_forward(x, w, b, oh, pool)
    else:
        flat, _ = _pooled_flat(x, pool)
        logits = flat @ w + b
        loss_i, dl = _ce_from_logits(logits.astype(jnp.float32),
                                     oh.astype(jnp.float32))
    return jnp.mean(loss_i), dl


def _backward(pool, x, w, dl_scaled):
    """Shared cotangent math from dlogits (already gbar/B-scaled, f32)."""
    flat, pooled = _pooled_flat(x, pool)
    dw = (flat.astype(jnp.float32).T @ dl_scaled).astype(w.dtype)
    db = jnp.sum(dl_scaled, axis=0).astype(w.dtype)
    dflat = dl_scaled @ w.astype(jnp.float32).T  # (B, D|C) f32
    if pool == "gap":
        B, H, W, C = x.shape
        dx = jnp.broadcast_to(
            dflat[:, None, None, :] / (H * W), (B, H, W, C)
        ).astype(x.dtype)
    elif pool == "max2":
        dpool = dflat.reshape(pooled.shape)
        p0, p1, p2, p3 = _phases(x)
        # First-match tie routing in row-major window order — XLA's
        # select-and-scatter semantics, so ReLU-zero ties route
        # identically to the unfused max-pool gradient.
        m0 = p0 == pooled
        m1 = (p1 == pooled) & ~m0
        m2 = (p2 == pooled) & ~(m0 | m1)
        m3 = (p3 == pooled) & ~(m0 | m1 | m2)
        dx = jnp.zeros(x.shape, jnp.float32)
        z = jnp.zeros((), jnp.float32)
        dx = dx.at[:, 0::2, 0::2, :].set(jnp.where(m0, dpool, z))
        dx = dx.at[:, 0::2, 1::2, :].set(jnp.where(m1, dpool, z))
        dx = dx.at[:, 1::2, 0::2, :].set(jnp.where(m2, dpool, z))
        dx = dx.at[:, 1::2, 1::2, :].set(jnp.where(m3, dpool, z))
        dx = dx.astype(x.dtype)
    else:
        dx = dflat.reshape(x.shape).astype(x.dtype)
    return dx, dw, db


@functools.lru_cache(maxsize=None)
def _tail_fn(pool: str):
    @jax.custom_vjp
    def tail(x, w, b, oh):
        return _forward(x, w, b, oh, pool)[0]

    def fwd(x, w, b, oh):
        loss, dl = _forward(x, w, b, oh, pool)
        return loss, (x, w, dl)

    def bwd(res, gbar):
        x, w, dl = res
        dl_scaled = dl * (gbar.astype(jnp.float32) / dl.shape[0])
        dx, dw, db = _backward(pool, x, w, dl_scaled)
        return dx, dw, db, jnp.zeros((dl.shape[0], w.shape[-1]), jnp.float32)

    tail.defvjp(fwd, bwd)
    return tail


def fused_tail_loss(x, w, b, labels, *, pool: str = "none") -> jax.Array:
    """Mean softmax-CE loss of the fused tail — a drop-in for
    ``cross_entropy(Dense.apply(...pool/flatten...), labels)``.

    x: tail input — (B, H, W, C) for "max2"/"gap" (H, W even for max2),
    (B, D) or (B, H, W, C) for "none". w: (D, K) Dense weight in flatten
    order, b: (K,). labels: (B,) int class ids. Returns the f32 scalar
    mean loss; its VJP emits dlogits from the forward.
    """
    if pool not in POOLS:
        raise ValueError(f"unknown pool {pool!r} (one of {POOLS})")
    if pool == "max2" and (x.shape[1] % 2 or x.shape[2] % 2):
        raise ValueError(
            f"max2 tail needs even spatial dims, got {x.shape[1:3]}"
        )
    oh = jax.nn.one_hot(labels, w.shape[-1], dtype=jnp.float32)
    return _tail_fn(pool)(x, w, b, oh)
