"""parallel_cnn_tpu — a TPU-native training framework with the capabilities of
Tamerkobba/Parallel-CNN.

The reference implements a hand-rolled LeNet-style CNN trainer (conv →
trainable-pool → FC, sigmoid everywhere, per-sample SGD) four times over:
Sequential C++, OpenMP, MPI and CUDA backends (see SURVEY.md). This package
re-expresses those capabilities idiomatically for TPU:

- ``data``     — idx-ubyte MNIST ingestion (NumPy + native C++ loader),
                 synthetic fallback, sharded host→HBM batching.
- ``ops``      — the per-layer forward/backward kernel library. Two paths:
                 ``ops.reference`` (jax.numpy/lax, bit-faithful to the
                 Sequential backend's numerics contract) and ``ops.pallas``
                 (compiled Mosaic TPU kernels, the CUDA-backend analog).
- ``models``   — the LeNet-ref parity model plus a growing model zoo.
- ``parallel`` — mesh abstraction, data-parallel `shard_map` training,
                 intra-op output-space decomposition (the MPI-backend analog),
                 multi-host init (the `mpirun` analog).
- ``train``    — jit-compiled train steps, epoch drivers, checkpointing.
- ``utils``    — correct (block_until_ready) per-phase timing, metrics.
"""

__version__ = "0.1.0"

from parallel_cnn_tpu.config import Config  # noqa: F401
