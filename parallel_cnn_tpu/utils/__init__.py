from parallel_cnn_tpu.utils.timing import PhaseTimer, Stopwatch  # noqa: F401
