"""Structured training metrics (replaces the reference's bare printf
telemetry — `error: %e, time_on_cpu: %lf` at Sequential/Main.cpp:174 —
with machine-readable records; SURVEY.md §5 "Metrics / logging").

One JSONL record per event: {"step": …, "epoch": …, metrics…, "ts": …}.
Sinks compose: file (JSONL), stdout, and an in-memory buffer for tests and
notebook use. Scalars are coerced to Python floats (device arrays block
until ready exactly once, at record time — sync-correct like utils/timing).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, TextIO


def _scalar(v: Any) -> Any:
    if isinstance(v, (int, str, bool)) or v is None:
        return v
    return float(v)  # numpy / jax scalars (blocks on device values)


class MetricsLogger:
    """Append-only metrics sink."""

    def __init__(
        self,
        path: Optional[str] = None,
        echo: bool = False,
        keep_in_memory: bool = True,
    ):
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._file: Optional[TextIO] = open(path, "a") if path else None
        self._echo = echo
        self.records: List[Dict[str, Any]] = [] if keep_in_memory else None

    def record(self, **values: Any) -> Dict[str, Any]:
        rec = {k: _scalar(v) for k, v in values.items()}
        rec["ts"] = time.time()
        if self.records is not None:
            self.records.append(rec)
        line = json.dumps(rec)
        if self._file:
            self._file.write(line + "\n")
            self._file.flush()
        if self._echo:
            print(line)
        return rec

    def close(self) -> None:
        if self._file:
            self._file.close()
            self._file = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def throughput(n_items: int, seconds: float) -> float:
    """items/sec with a zero-guard."""
    return n_items / seconds if seconds > 0 else float("inf")


class Histogram:
    """Streaming histogram over fixed log-spaced bins, with percentiles.

    Built for latency telemetry (serve/ and the benches/run.py latency
    rows): O(1) memory regardless of sample count, O(1) record, and
    p50/p90/p99 queries whose error is bounded by the bin ratio — with
    ``bins`` spanning [lo, hi), each bin covers a factor of
    (hi/lo)**(1/bins), so the default 96 bins over [1e-5 s, 100 s) put
    every quantile within ~±9% of truth. Exact count/sum/min/max ride
    alongside, and percentile answers are clamped into [min, max] so a
    single-sample histogram reports that sample, not a bin midpoint.

    Values below ``lo`` land in the first bin, values >= ``hi`` in the
    last (counted, never dropped). Thread-safe: record() is called from
    batcher worker and client threads concurrently.
    """

    def __init__(self, lo: float = 1e-5, hi: float = 100.0, bins: int = 96):
        if not (0 < lo < hi) or bins < 2:
            raise ValueError(f"need 0 < lo < hi and bins >= 2, got "
                             f"lo={lo} hi={hi} bins={bins}")
        self.lo, self.hi, self.bins = float(lo), float(hi), int(bins)
        self._log_lo = math.log(lo)
        self._inv_width = bins / (math.log(hi) - math.log(lo))
        self.counts = [0] * bins
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._lock = threading.Lock()

    def _index(self, v: float) -> int:
        if v < self.lo:
            return 0
        i = int((math.log(v) - self._log_lo) * self._inv_width)
        return min(max(i, 0), self.bins - 1)

    def record(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.counts[self._index(v)] += 1
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    def merge(self, other: "Histogram") -> None:
        """Fold another histogram (same binning) into this one."""
        if (other.lo, other.hi, other.bins) != (self.lo, self.hi, self.bins):
            raise ValueError("histogram binning mismatch")
        with self._lock:
            for i, c in enumerate(other.counts):
                self.counts[i] += c
            self.count += other.count
            self.sum += other.sum
            for m in (other.min,):
                if m is not None:
                    self.min = m if self.min is None else min(self.min, m)
            for m in (other.max,):
                if m is not None:
                    self.max = m if self.max is None else max(self.max, m)

    def percentile(self, p: float) -> Optional[float]:
        """p-th percentile (p in [0, 100]); None on an empty histogram.

        Returns the geometric midpoint of the bin holding the p-th
        sample, clamped into the exact observed [min, max]."""
        if not 0 <= p <= 100:
            raise ValueError(f"percentile must be in [0, 100], got {p}")
        with self._lock:
            if self.count == 0:
                return None
            rank = p / 100.0 * self.count
            seen = 0
            for i, c in enumerate(self.counts):
                seen += c
                if seen >= rank and c:
                    ratio = (self.hi / self.lo) ** (1.0 / self.bins)
                    mid = self.lo * ratio ** (i + 0.5)
                    return min(max(mid, self.min), self.max)
            return self.max

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def summary(self, scale: float = 1.0) -> Dict[str, Any]:
        """count/mean/min/max/p50/p90/p99 as plain floats, each value
        multiplied by ``scale`` (e.g. 1e3 for seconds → milliseconds)."""
        with self._lock:
            count = self.count
        if count == 0:
            return {"count": 0}
        out: Dict[str, Any] = {
            "count": count,
            "mean": self.mean * scale,
            "min": self.min * scale,
            "max": self.max * scale,
        }
        for p in (50, 90, 99):
            out[f"p{p}"] = self.percentile(p) * scale
        return out
