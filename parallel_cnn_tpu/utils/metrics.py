"""Structured training metrics (replaces the reference's bare printf
telemetry — `error: %e, time_on_cpu: %lf` at Sequential/Main.cpp:174 —
with machine-readable records; SURVEY.md §5 "Metrics / logging").

One JSONL record per event: {"step": …, "epoch": …, metrics…, "ts": …}.
Sinks compose: file (JSONL), stdout, and an in-memory buffer for tests and
notebook use. Scalars are coerced to Python floats (device arrays block
until ready exactly once, at record time — sync-correct like utils/timing).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, TextIO


def _scalar(v: Any) -> Any:
    if isinstance(v, (int, str, bool)) or v is None:
        return v
    return float(v)  # numpy / jax scalars (blocks on device values)


class MetricsLogger:
    """Append-only metrics sink."""

    def __init__(
        self,
        path: Optional[str] = None,
        echo: bool = False,
        keep_in_memory: bool = True,
    ):
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._file: Optional[TextIO] = open(path, "a") if path else None
        self._echo = echo
        self.records: List[Dict[str, Any]] = [] if keep_in_memory else None

    def record(self, **values: Any) -> Dict[str, Any]:
        rec = {k: _scalar(v) for k, v in values.items()}
        rec["ts"] = time.time()
        if self.records is not None:
            self.records.append(rec)
        line = json.dumps(rec)
        if self._file:
            self._file.write(line + "\n")
            self._file.flush()
        if self._echo:
            print(line)
        return rec

    def close(self) -> None:
        if self._file:
            self._file.close()
            self._file = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def throughput(n_items: int, seconds: float) -> float:
    """items/sec with a zero-guard."""
    return n_items / seconds if seconds > 0 else float("inf")
