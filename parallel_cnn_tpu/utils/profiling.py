"""Per-phase layer profiling (≙ the reference's per-phase accumulators and
the paper's Tables 4-8: conv / pooling / fully-connected / gradient times).

The reference times each phase with host clock() inside forward_pass/
back_pass (Sequential/Main.cpp:80-102,113-141) — and in the CUDA backend
forgets to synchronize, timing kernel *launches* (SURVEY.md B11). Here each
phase is its own jitted program timed with block_until_ready after a
warm-up compile, so numbers are device-execution time.

Phases mirror the reference decomposition:
    conv  ≙ fp_c1 + sigmoid           (Sequential/Main.cpp:80-85)
    pool  ≙ fp_s1 + sigmoid           (:87-93)
    fc    ≙ fp_preact_f/bias + sigmoid (:95-101)
    grad  ≙ the whole back_pass        (:107-144)

Also wraps `jax.profiler` tracing for real XLA-level profiles.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Callable, Dict

import jax
import jax.numpy as jnp

from parallel_cnn_tpu.ops import reference as ops


def _tree_checksum(tree) -> jax.Array:
    return sum(jnp.sum(leaf) for leaf in jax.tree_util.tree_leaves(tree))


def _time_fn(fn: Callable, x: jax.Array, *rest, repeats: int = 10) -> float:
    """Mean seconds per call of fn(x, *rest), device-execution time.

    Two TPU-relay measurement hazards (the same two bench.py documents;
    the reference's unsync'd clock() timing is SURVEY.md B11):
    - byte-identical (executable, args) replays can be memoized, so the
      warm-up uses perturbed args and repeats run INSIDE one program,
      each iteration's input chained through the carry (loop-variant, so
      XLA cannot hoist the body);
    - block_until_ready can return before remote execution finishes, so
      the only barrier used is a host readback (float()).

    Repeat-until-resolvable (round-6 fix for the `phase_fc = 0.0` rows
    in the paper tables): a microsecond phase under a ~ms relay RTT used
    to clamp to 0.0 when the overhead subtraction went negative — a
    zero that poisoned every downstream speedup column. Now the repeat
    count auto-scales (×8 per attempt, like benches/run.py._sync_time)
    until the loop's elapsed time dominates the measured overhead, so
    the subtraction is a ≤25% correction; if even the largest loop is
    overhead-bound, the UN-subtracted mean is returned — an upper
    bound, but honest and NONZERO, so every table row computes.
    """

    def make_looped(r: int):
        @jax.jit
        def looped(x, *rest):
            def body(_, s):
                out = fn(x + s * 1e-30, *rest)
                return s + _tree_checksum(out) * 1e-30

            return jax.lax.fori_loop(0, r, body, jnp.float32(0.0))

        return looped

    # Dispatch + readback floor (the relay RTT under a tunneled chip —
    # ~ms, which would otherwise swamp these microsecond phases): measured
    # on a trivial chained program and subtracted below.
    tiny = jax.jit(lambda v: v + 1.0)
    v = tiny(jnp.float32(0.0))
    float(v)
    t0 = time.perf_counter()
    float(tiny(v))
    overhead = time.perf_counter() - t0

    r = max(repeats, 1)
    elapsed = 0.0
    for _ in range(4):
        looped = make_looped(r)
        float(looped(x + 1.0, *rest))  # compile + warm on distinct args
        t0 = time.perf_counter()
        float(looped(x, *rest))  # distinct from warm-up → real execution
        elapsed = time.perf_counter() - t0
        if elapsed > 0 and elapsed - overhead > 0 and elapsed >= 4 * overhead:
            return (elapsed - overhead) / r
        r *= 8
    r //= 8  # the repeat count the final attempt actually ran
    return max(elapsed / r, 1e-12)


def profile_phases(
    params: ops.Params, xs: jax.Array, ys: jax.Array, repeats: int = 10
) -> Dict[str, float]:
    """Per-phase mean seconds for a batch (the paper's table decomposition).

    Returns {"conv", "pool", "fc", "grad", "total_forward"}.
    """
    sigmoid = jax.nn.sigmoid

    # Timed input first: _time_fn perturbs it per loop iteration.
    def conv(x, p):
        return sigmoid(
            jax.vmap(lambda s: ops.conv_c1_forward(s, p["c1"]["w"], p["c1"]["b"]))(x)
        )

    def pool(oc, p):
        return sigmoid(
            jax.vmap(lambda s: ops.pool_s1_forward(s, p["s1"]["w"], p["s1"]["b"]))(oc)
        )

    def fc(os_, p):
        return sigmoid(
            jax.vmap(lambda s: ops.fc_forward(s, p["f"]["w"], p["f"]["b"]))(os_)
        )

    def fwd(x, p):
        return jax.vmap(lambda s: ops.forward(p, s).out_f)(x)

    def grad(x, p, y):
        _, grads = jax.vmap(ops.value_and_ref_grads, in_axes=(None, 0, 0))(p, x, y)
        return jax.tree_util.tree_map(lambda g: jnp.mean(g, axis=0), grads)

    out_c1 = jax.jit(conv)(xs, params)
    out_s1 = jax.jit(pool)(out_c1, params)

    return {
        "conv": _time_fn(conv, xs, params, repeats=repeats),
        "pool": _time_fn(pool, out_c1, params, repeats=repeats),
        "fc": _time_fn(fc, out_s1, params, repeats=repeats),
        "grad": _time_fn(grad, xs, params, ys, repeats=repeats),
        "total_forward": _time_fn(fwd, xs, params, repeats=repeats),
    }


def report(phase_seconds: Dict[str, float], n_images: int) -> str:
    """Render the paper-style per-layer table (≙ PDF Table 4 shape)."""
    lines = [f"{'phase':<14}{'ms/batch':>12}{'images/sec':>14}"]
    for name, sec in phase_seconds.items():
        ips = n_images / sec if sec > 0 else float("inf")
        lines.append(f"{name:<14}{sec * 1e3:>12.3f}{ips:>14.0f}")
    return "\n".join(lines)


@contextmanager
def xla_trace(log_dir: str):
    """jax.profiler trace wrapper — open the result in XProf/TensorBoard.
    The real replacement for hand-rolled clock() spans."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
