"""Backend identification helpers.

The one subtlety worth a module: TPU hardware does not always present as
platform "tpu". Under the ambient `axon` relay (a PJRT plugin tunneling to
a real chip) the platform/backend name is "axon" — so naive
`jax.default_backend() == "tpu"` checks silently mis-detect real TPU
hardware (round 1 shipped Pallas kernels that interpreted on the real chip
for exactly this reason). Detection here keys on the device_kind too.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax

# Platform names known to front a real TPU.
_TPU_PLATFORMS = frozenset({"tpu", "axon"})


def is_tpu(devices: Optional[Sequence] = None) -> bool:
    """True iff the (default) backend executes on TPU hardware, including
    via relay plugins whose platform name is not literally "tpu"."""
    ds = list(devices) if devices is not None else jax.devices()
    if not ds:
        return False
    d = ds[0]
    platform = (getattr(d, "platform", "") or "").lower()
    kind = (getattr(d, "device_kind", "") or "").lower()
    return platform in _TPU_PLATFORMS or "tpu" in kind


def canonical_platform(devices: Optional[Sequence] = None) -> str:
    """"tpu" for any TPU-backed platform (native or relayed), else the raw
    platform name ("cpu", "gpu", ...). This is the label benchmarks report."""
    if is_tpu(devices):
        return "tpu"
    ds = list(devices) if devices is not None else jax.devices()
    return (getattr(ds[0], "platform", "") or "unknown").lower() if ds else "unknown"
