"""Correct device-aware timing (fixes the reference's measurement bugs).

The reference times with host `clock()` around kernel launches and never
synchronizes the device — its CUDA numbers measure launch overhead, not GPU
execution (CUDA/main.cu:71-107, SURVEY.md B11). Here every span end blocks
on the traced value (`block_until_ready`) so wall-time covers actual device
work, and per-phase accumulators (≙ total_convolution_time etc.,
Sequential/Main.cpp:11) are first-class.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Optional

import jax


class Stopwatch:
    """Accumulating wall-clock timer; use as a context manager per span."""

    def __init__(self) -> None:
        self.total = 0.0
        self.spans = 0
        self._t0: Optional[float] = None

    def __enter__(self) -> "Stopwatch":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.total += time.perf_counter() - self._t0
        self.spans += 1
        self._t0 = None


class PhaseTimer:
    """Named per-phase accumulators (≙ the four totals at
    Sequential/Main.cpp:11,51-54), but sync-correct: pass the phase's output
    arrays to `stop` and the span blocks until they are actually computed."""

    def __init__(self) -> None:
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextmanager
    def phase(self, name: str, result=None):
        t0 = time.perf_counter()
        out = {}
        yield out
        value = out.get("result", result)
        if value is not None:
            jax.block_until_ready(value)
        self.totals[name] += time.perf_counter() - t0
        self.counts[name] += 1

    def report(self) -> str:
        lines = [
            f"Total {name} time: {ms * 1000.0:.3f} ms"
            for name, ms in sorted(self.totals.items())
        ]
        return "\n".join(lines)
