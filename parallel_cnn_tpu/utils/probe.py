"""Shared TPU-backend probe: ONE health-check implementation for every
driver-facing tool (bench.py's ``_resolve_platform`` wait loop and
benches/watch.py's ``probe_once`` both import from here — round-5 shipped
two hand-rolled copies whose behavior drifted).

Contract (round-1 lesson, BENCH_r01): backend init through the axon
relay can hang indefinitely when the tunnel is down, so health is ALWAYS
probed in a subprocess with a hard timeout — the subprocess absorbs the
hang, the caller never blocks past ``timeout``.

PYTHONPATH handling (the round-5 ``PYTHONPATH=$PWD`` clobber trap): the
probe subprocess must see the same import tree as the caller — including
any sitecustomize hook that registers the axon plugin — so the repo root
is APPENDED to the inherited PYTHONPATH, never assigned over it. A
driver that exported its own PYTHONPATH keeps every entry.

Retry schedule: ``wait_for_tpu`` ramps 15 s → ``RETRY_BACKOFF_CAP`` and
then polls at the cap, which is also the watcher's default probe
interval — bench and watcher see the same worst-case heal latency, so
the bench no longer concedes to CPU on a schedule the watcher would
have caught.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Callable, Optional, Tuple

# Two lines: the configured platform list (the axon sitecustomize hook
# sets e.g. "axon,cpu"), then the live default device's platform. The
# LAST stdout line is the live platform (stray stdout noise lands
# before it); the second-to-last is the configured list.
_PROBE_SNIPPET = (
    "import jax; print(jax.config.jax_platforms or '');"
    " print(jax.devices()[0].platform)"
)

# Shared probe retry schedule: backoff ramps STEP·attempt up to CAP,
# then polls at CAP. benches/watch.py's default --interval is CAP too.
RETRY_BACKOFF_STEP = 15.0
RETRY_BACKOFF_CAP = 60.0


def probe_env() -> dict:
    """Subprocess env with the repo root APPENDED to PYTHONPATH.

    Append — never assign: replacing PYTHONPATH (round 5's
    ``PYTHONPATH=$PWD``) silently dropped driver-supplied entries and
    with them the sitecustomize hook that registers the axon TPU
    plugin, so probes reported healthy CPU boxes as the platform truth.
    """
    env = dict(os.environ)  # graftcheck: disable=env-outside-config -- subprocess must inherit the FULL parent environment (see docstring: allowlists dropped the plugin hook)
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    if root not in parts:
        parts.append(root)
    env["PYTHONPATH"] = os.pathsep.join(parts)
    return env


def probe_platform(
    timeout: float = 120.0, runner=subprocess.run
) -> Tuple[str, str]:
    """One subprocess probe → (configured_platforms, live_platform).

    ("", "") on nonzero exit, timeout, or exec failure — indistinguishable
    from "down", which is the right default through a flaky relay.
    """
    try:
        proc = runner(
            [sys.executable, "-c", _PROBE_SNIPPET],
            capture_output=True,
            text=True,
            timeout=timeout,
            env=probe_env(),
        )
    except (subprocess.TimeoutExpired, OSError):
        return "", ""
    if getattr(proc, "returncode", 1) != 0:
        return "", ""
    lines = (proc.stdout or "").splitlines()
    configured = lines[-2].strip() if len(lines) >= 2 else ""
    live = lines[-1].strip() if lines else ""
    return configured, live


def probe_once(timeout: float = 120.0, runner=subprocess.run) -> bool:
    """True iff a fresh process sees a non-CPU default jax backend.

    A probe that *succeeds* but reports ``cpu`` (axon plugin loaded, no
    TPU exposed) counts as down — that mode is exactly what produced the
    CPU-fallback BENCH_r03/r04 artifacts.
    """
    _, live = probe_platform(timeout, runner)
    return bool(live) and live != "cpu"


def wait_for_tpu(
    wait_budget: float,
    timeout: float = 120.0,
    probe: Callable[[float], Tuple[str, str]] = probe_platform,
    sleep: Callable[[float], None] = time.sleep,
    log: Optional[Callable[[str], None]] = None,
    now: Callable[[], float] = time.perf_counter,
) -> bool:
    """Probe-with-backoff until a TPU shows up or the budget runs out.

    Returns True the moment a probe reports a healthy non-CPU backend.
    A clean probe that reports cpu with NO non-cpu platform configured
    means there is probably no TPU plugin to wait FOR — concede after
    TWO consecutive such probes instead of burning the whole wait
    budget on a plain CPU box. (Two, not one: on a TPU VM whose plugin
    failed transiently, jax_platforms is also unset and the first probe
    can report cpu — the second probe after backoff catches the heal. A
    flaky axon relay, by contrast, either hangs the probe or shows a
    non-cpu entry in the platform list and keeps the full wait.)
    """
    t0 = now()
    attempt = 0
    clean_cpu_streak = 0
    while True:
        attempt += 1
        configured, live = probe(timeout)
        if live and live != "cpu":
            return True
        if live and not any(
            p and p != "cpu" for p in configured.split(",")
        ):
            clean_cpu_streak += 1
            if clean_cpu_streak >= 2:
                return False  # plain CPU environment: nothing to wait for
        else:
            clean_cpu_streak = 0
        remaining = wait_budget - (now() - t0)
        if remaining <= 0:
            return False
        backoff = min(
            RETRY_BACKOFF_STEP * attempt, RETRY_BACKOFF_CAP, remaining
        )
        if log is not None:
            log(
                f"backend probe {attempt} found no TPU; retrying in "
                f"{backoff:.0f}s ({remaining:.0f}s of TPU wait budget left)"
            )
        sleep(backoff)
