"""Benchmark harness (≙ the reference paper's result tables, SURVEY.md §6 /
C19): per-layer phase times (Tables 4-7 shape), end-to-end epoch time and
throughput (Tables 1, 8), DP scaling over the device mesh (Tables 2-3
shape), and model-zoo configs (BASELINE.json #3-#5).

    python benches/run.py [--quick] [--json PATH] [--md PATH]

Every row reports value + unit + the reference baseline it compares
against (from BASELINE.md, measured on the reference's own hardware — a
context gap the report states rather than hides). The headline driver
contract stays in bench.py; this harness is the full table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from dataclasses import asdict, dataclass
from typing import List, Optional

# Runnable as a plain script: the repo root (parent of benches/) must be
# importable for `parallel_cnn_tpu`.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# The ambient platform plugin snapshots JAX_PLATFORMS before user code runs
# (see tests/conftest.py); jax.config.update is the reliable override — so
# honor PCNN_JAX_PLATFORMS here for hermetic CPU runs.
if os.environ.get("PCNN_JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["PCNN_JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

# Persistent XLA compilation cache (works through the relay): one shared
# implementation with the driver headline script — repeat suite runs skip
# recompiles.
import bench as _bench

_bench._enable_compile_cache()

# Reference numbers (BASELINE.md; paper PDF §6 Tables 1-8).
SEQ_EPOCH_S = 102.317095          # Table 1 (60k samples, CPU VM)
CUDA_EPOCH_S = 2.9969857          # Table 8 (T4)
CUDA_CONV_MS = 90.173             # Table 5 (per epoch, T4)
CUDA_POOL_MS = 5.1927             # Table 6
CUDA_FC_MS = 0.386624             # Table 7
EPOCH_IMAGES = 60_000


@dataclass
class Row:
    name: str
    value: float
    unit: str
    baseline: Optional[float] = None
    baseline_src: str = ""
    speedup: Optional[float] = None
    # Relay-variance protocol (same as bench.py's headline): throughput
    # rows are the MEDIAN of value_samples same-session measurements with
    # the min–max range alongside; single-sample rows leave range None.
    value_range: Optional[List[float]] = None
    value_samples: int = 1

    def finish(self) -> "Row":
        if self.baseline is not None and self.value > 0:
            # value/baseline semantics depend on unit: time-like units
            # invert (smaller is better).
            if self.unit.endswith("/sec"):
                self.speedup = round(self.value / self.baseline, 2)
            else:
                self.speedup = round(self.baseline / self.value, 2)
        return self


_drain_cache: dict = {}


def _drain(tree) -> None:
    """TRUE execution barrier for a pytree through the tunneled chip.

    Neither block_until_ready nor a single-leaf readback is enough there:
    block_until_ready can return while compile + execution are still in
    flight, and one leaf can complete long before the rest of the program
    (measured: reading only ZooState's first leaf — an optimizer count
    that increments without touching the heavy compute — timed ResNet-50
    @224² at a physically impossible 33 ms/step). So: jit a scalar that
    consumes EVERY leaf and read that scalar back — the one host readback
    cannot materialize until the whole program has run."""
    leaves = [l for l in jax.tree_util.tree_leaves(tree) if hasattr(l, "dtype")]
    key = tuple((l.shape, str(l.dtype)) for l in leaves)
    fn = _drain_cache.get(key)
    if fn is None:
        def _reduce(*ls):
            tot = jnp.float32(0.0)
            for l in ls:
                tot = tot + jnp.sum(jnp.abs(l.astype(jnp.float32)))
            return tot

        fn = jax.jit(_reduce)
        _drain_cache[key] = fn
    np.asarray(fn(*leaves))


_tiny_chain = jax.jit(lambda v: v + 1.0)


def _rtt() -> float:
    """Min-of-3 readback RTT on a trivial chained program (min, not mean:
    RTT jitter only ever ADDS latency, so the smallest sample is the
    least-biased estimate of the floor being subtracted)."""
    v = _tiny_chain(jnp.float32(0.0))
    np.asarray(v)
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        v = _tiny_chain(v)
        np.asarray(v)
        samples.append(time.perf_counter() - t0)
    return min(samples)


def _sync_time(thunk, repeats: int) -> float:
    """Chained-dispatch timing: warmup drained, `repeats` chained calls,
    one full drain, minus the measured readback RTT (as bench.py does —
    the RTT otherwise dominates short rows through the relay, e.g.
    cifar_cnn's ~6 ms/step of compute under a ~100 ms readback).

    When the timed region doesn't clear the RTT — a cheap row like
    --quick cifar_cnn at ~6 ms/step under a ~100 ms relay readback — the
    measurement is auto-retried with the repeat count scaled up until
    compute dominates (target: elapsed >= 4× RTT), rather than raising
    and killing the whole suite. A clamped near-zero denominator would
    report absurd throughput as if legitimate, so after the retry budget
    is spent we still raise; main() converts that into a labeled error
    row instead of an aborted run."""
    out = thunk(None)
    _drain(out)
    carry = out
    for _attempt in range(4):
        t0 = time.perf_counter()
        for _ in range(repeats):
            carry = thunk(carry)
        _drain(carry)
        elapsed = time.perf_counter() - t0
        rtt = _rtt()
        corrected = elapsed - rtt
        if corrected > 0 and elapsed >= 4 * rtt:
            return corrected / repeats
        ran = repeats  # what this attempt actually executed (for the error)
        # Scale repeats so the next attempt lands ~8× over the RTT floor —
        # capped: an absurd RTT (relay glitch, or a test stubbing it) must
        # exhaust the 4 attempts and raise, not spin for 8·rtt/per_rep
        # iterations.
        per_rep = max(elapsed / repeats, 1e-6)
        repeats = min(max(repeats * 2, int(8 * rtt / per_rep) + 1), 4096)
    raise RuntimeError(
        f"timed region ({elapsed * 1e3:.1f} ms over {ran} repeats, RTT "
        f"{rtt * 1e3:.1f} ms) never exceeded the readback RTT after repeat "
        "auto-scaling; the row's compute is unmeasurably small through "
        "this relay"
    )


def _n_samples() -> int:
    """Same-session sample count for throughput rows (bench.py protocol:
    ≥5 on-chip — three left the run-to-run range wider than the effect
    sizes being claimed; 3 on the CPU fallback, so the median+range stays
    meaningful off-TPU too — a single sample made cross-round CPU
    comparisons meaningless, see docs/bench_results.md)."""
    from parallel_cnn_tpu.utils.backend import canonical_platform

    return max(int(os.environ.get(
        "PCNN_BENCH_SAMPLES", "5" if canonical_platform() == "tpu" else "3"
    )), 1)


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _sampled_ips(thunk, repeats: int, images_per_call: float):
    """N independent _sync_time samples → (median img/s, [min, max], n).

    Each sample is a full warmed, chained, RTT-corrected measurement; the
    median is the row value, the range is the honesty bar on it."""
    secs = [_sync_time(thunk, repeats) for _ in range(_n_samples())]
    ips = [round(images_per_call / s, 1) for s in secs]
    return _median(ips), [min(ips), max(ips)], len(ips)


def bench_lenet_throughput(quick: bool) -> List[Row]:
    """End-to-end minibatch training throughput (≙ Table 8 / BASELINE.md
    derived ≈20k img/s CUDA)."""
    from parallel_cnn_tpu.models import lenet_ref
    from parallel_cnn_tpu.ops import reference as ops
    from parallel_cnn_tpu.ops.activations import apply_grad

    batch = 2048
    steps = 8 if quick else 29
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.uniform(0, 1, (steps, batch, 28, 28)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 10, (steps, batch)).astype(np.int32))
    params = lenet_ref.init(jax.random.key(0))

    @jax.jit
    def epoch(params, images, labels):
        def body(p, xy):
            x, y = xy
            errs, grads = jax.vmap(ops.value_and_ref_grads, in_axes=(None, 0, 0))(p, x, y)
            return (
                apply_grad(p, jax.tree_util.tree_map(lambda g: jnp.mean(g, axis=0), grads), 0.1),
                jnp.mean(errs),
            )

        p, errs = jax.lax.scan(body, params, (images, labels))
        return p, jnp.mean(errs)

    def thunk(carry):
        p = carry[0] if carry is not None else params
        return epoch(p, images, labels)

    ips, ips_range, n_s = _sampled_ips(
        thunk, repeats=2 if quick else 5, images_per_call=steps * batch
    )
    epoch_s = EPOCH_IMAGES / ips
    return [
        Row("train_throughput_batched", round(ips, 1), "images/sec",
            EPOCH_IMAGES / CUDA_EPOCH_S, "CUDA Table 8",
            value_range=ips_range, value_samples=n_s).finish(),
        Row("epoch_time_batched", round(epoch_s, 4), "sec/epoch(60k)",
            CUDA_EPOCH_S, "CUDA Table 8", value_samples=n_s).finish(),
        Row("epoch_time_vs_sequential", round(epoch_s, 4), "sec/epoch(60k)",
            SEQ_EPOCH_S, "Sequential Table 1", value_samples=n_s).finish(),
    ]


def bench_lenet_parity_epoch(quick: bool) -> List[Row]:
    """Strict-parity per-sample SGD epoch (≙ Table 1's workload: batch=1,
    60k sequential updates — as ONE lax.scan program)."""
    from parallel_cnn_tpu.models import lenet_ref
    from parallel_cnn_tpu.train import step as step_lib

    n = 6_000 if quick else 60_000
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.uniform(0, 1, (n, 28, 28)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 10, (n,)).astype(np.int32))
    params = lenet_ref.init(jax.random.key(0))

    def thunk(carry):
        p = carry[0] if carry is not None else params
        return step_lib.scan_epoch(
            jax.tree_util.tree_map(jnp.array, p), images, labels, 0.1
        )

    sec = _sync_time(thunk, repeats=1 if quick else 2)
    epoch_s = sec * (EPOCH_IMAGES / n)
    return [
        Row("epoch_time_per_sample_sgd", round(epoch_s, 3), "sec/epoch(60k)",
            SEQ_EPOCH_S, "Sequential Table 1").finish()
    ]


def bench_phases(quick: bool) -> List[Row]:
    """Per-layer forward phases (≙ Tables 4-7). Reference CUDA rows are
    per-epoch totals on a T4; ours are scaled to the same 60k-image epoch."""
    from parallel_cnn_tpu.models import lenet_ref
    from parallel_cnn_tpu.utils import profiling

    batch = 2048
    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.uniform(0, 1, (batch, 28, 28)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, (batch,)).astype(np.int32))
    params = lenet_ref.init(jax.random.key(0))
    phases = profiling.profile_phases(
        params, xs, ys, repeats=10 if quick else 50
    )
    scale = EPOCH_IMAGES / batch  # per-batch → per-60k-epoch
    refs = {"conv": CUDA_CONV_MS, "pool": CUDA_POOL_MS, "fc": CUDA_FC_MS}
    rows = []
    for name, sec in phases.items():
        rows.append(
            Row(f"phase_{name}", round(sec * 1e3 * scale, 3), "ms/epoch(60k)",
                refs.get(name), f"CUDA Table {dict(conv=5, pool=6, fc=7).get(name, '-')}" if name in refs else "").finish()
        )
    return rows


def bench_ops_paths(quick: bool) -> List[Row]:
    """Path A (jnp/lax) vs path B (Pallas/Mosaic kernels) on the SAME
    minibatch train step — the A-vs-B comparison the CUDA backend implies
    by wiring its kernels into its driver (CUDA/main.cu:56-163). On TPU
    path B is compiled Mosaic; elsewhere it runs the Pallas interpreter
    (orders of magnitude slower — the row still proves numerical parity)."""
    from parallel_cnn_tpu.models import lenet_ref
    from parallel_cnn_tpu.train import step as step_lib
    from parallel_cnn_tpu.utils.backend import canonical_platform

    batch = 2048
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(0, 1, (batch, 28, 28)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, (batch,)).astype(np.int32))

    on_tpu = canonical_platform() == "tpu"
    repeats = (2 if quick else 5) if not on_tpu else (10 if quick else 30)
    rows = []
    paths = [("reference", step_lib.batched_step)]
    # Interpreted Pallas at batch=2048 is minutes/step on CPU; bench the
    # kernel path only where it compiles (TPU) unless explicitly forced.
    if on_tpu or os.environ.get("PCNN_BENCH_PALLAS"):
        paths.append(("pallas", step_lib.pallas_batched_step))
    else:
        print("[bench_ops_paths] pallas row skipped (no TPU; "
              "set PCNN_BENCH_PALLAS=1 to force interpret mode)", flush=True)
    for name, step in paths:
        params = lenet_ref.init(jax.random.key(0))

        def thunk(carry, step=step, params=params):
            p = carry[0] if carry is not None else params
            return step(p, x, y, 0.1)

        ips, ips_range, n_s = _sampled_ips(
            thunk, repeats=repeats, images_per_call=batch
        )
        rows.append(
            Row(f"ops_{name}_step", round(ips, 1), "images/sec",
                EPOCH_IMAGES / CUDA_EPOCH_S, "CUDA Table 8",
                value_range=ips_range, value_samples=n_s).finish()
        )
    return rows


def bench_dp_scaling(quick: bool) -> List[Row]:
    """DP scaling over the data mesh axis (≙ Tables 2-3's speedup/efficiency
    shape). Uses however many devices the platform exposes (8 virtual CPU
    devices under the test env; one real chip on the tunnel — skipped there)."""
    from parallel_cnn_tpu.config import MeshConfig
    from parallel_cnn_tpu.models import lenet_ref
    from parallel_cnn_tpu.parallel import data_parallel, mesh as mesh_lib

    n_dev = len(jax.devices())
    if n_dev < 2:
        return []
    rows = []
    global_batch = 1024
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(0, 1, (global_batch, 28, 28)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, (global_batch,)).astype(np.int32))
    sizes = [d for d in (1, 2, 4, 8) if d <= n_dev]

    def time_dp(d: int, gb: int) -> float:
        """Seconds per DP step on d devices at global batch gb (shared
        scaffolding for the strong- and weak-scaling tables)."""
        mesh = mesh_lib.make_mesh(
            MeshConfig(data=d, model=1), devices=jax.devices()[:d]
        )
        step = data_parallel.make_dp_step(mesh, dt=0.1, global_batch=gb)
        params = mesh_lib.replicate(mesh, lenet_ref.init(jax.random.key(0)))
        reps = gb // x.shape[0] + 1
        xs, ys = mesh_lib.shard_batch(
            mesh,
            (jnp.tile(x, (reps, 1, 1))[:gb], jnp.tile(y, (reps,))[:gb]),
        )

        def thunk(carry, step=step, xs=xs, ys=ys, params=params):
            p = carry[0] if carry is not None else params
            return step(p, xs, ys)

        return _sync_time(thunk, repeats=3 if quick else 10)

    base_sec = None
    for d in sizes:
        sec = time_dp(d, global_batch)
        if base_sec is None:
            base_sec = sec
        rows.append(
            Row(f"dp_speedup_{d}dev", round(base_sec / sec, 3), "x vs 1dev",
                None, f"(MPI 2c: 1.53x, 4c: 1.02x — Table 2)").finish()
        )

    # Weak scaling: per-device batch FIXED (work grows with devices), the
    # regime DP actually targets — efficiency = throughput per device
    # relative to 1 device (Tables 2-3 report only strong scaling).
    per_dev = 256
    base_ips = None
    for d in sizes:
        gb = per_dev * d
        ips = gb / time_dp(d, gb)
        if base_ips is None:
            base_ips = ips
        rows.append(
            Row(f"dp_weak_efficiency_{d}dev",
                round(ips / (base_ips * d), 3), "throughput/dev vs 1dev",
                None, f"{round(ips, 0)} img/s total").finish()
        )
    return rows


def bench_comm(quick: bool) -> List[Row]:
    """Gradient-collective ablation on the zoo accum×mesh leg: the SAME
    explicit shard_map train step (cifar_cnn, accum_steps=2, all devices
    on the data axis) with only the comm algorithm varied —

      psum       monolithic lax.psum (XLA picks the algorithm),
      ring       bucketed ring reduce-scatter/all-gather with microbatch
                 comm/compute overlap (parallel/collectives.py),
      ring_bf16  ring + bf16-on-the-wire (half the ICI payload bytes).

    Because every variant shares one step body, the per-impl img/s rows
    isolate the collective schedule; the baseline_src column carries each
    variant's final-step loss delta vs psum, so the table double-checks
    the ≤1e-5 (ring) / ≤1e-2 (bf16) parity contract while it measures.

    Two further legs on the same model/batch:

    - Hierarchical: the device set re-folded into an emulated 2-host
      (host, device) mesh; `hier` / `hier_bf16` run the two-level rings
      (intra-host RS → host-axis shard exchange → all-gathers) against a
      `psum_hier` reference ON THE SAME MESH — BatchNorm batch stats are
      shard-local, so parity is only meaningful within one mesh shape.
    - ZeRO: the fused update-on-arrival step with replicated state
      (ZeRO-2, `zero2_ring`) vs resident 1/n shards + just-in-time f32
      param gathers at the step head (ZeRO-3, `zero3_ring`); the zero3
      row's baseline_src carries its throughput ratio vs zero2 — the
      memory-for-bandwidth trade's cost, which docs/collectives.md
      budgets at ≥0.9x.

    Final leg — the async straggler ablation (ASYNC_GATE, the playbook
    `async` mode's contract line): the virtual-clock harness
    (train/async_dp.py) runs sync ring vs bounded-staleness (S=2) vs
    EASGD on lenet, clean and under chaos `slow-worker@2:400`, and the
    gate demands BOTH directions — the async modes hold >= 0.8x their
    clean virtual throughput under the straggler while the sync ring is
    asserted to degrade below it (anti-vacuity), with the 3-step loss
    delta vs sync <= 1e-2 (stale clean+chaos, easgd clean) and the
    staleness ledger never exceeding S.  Virtual time is deterministic,
    so this leg is exact on CPU.

    On the 8-virtual-device CPU harness the "ICI" is shared-memory copies
    — ranking is indicative, the TPU run is the real evidence."""
    from parallel_cnn_tpu.config import CommConfig, FusedStepConfig, MeshConfig
    from parallel_cnn_tpu.data import synthetic
    from parallel_cnn_tpu.nn import cifar
    from parallel_cnn_tpu.train import zoo
    from parallel_cnn_tpu.parallel import mesh as mesh_lib

    n_dev = len(jax.devices())
    if n_dev < 2:
        return []
    mesh = mesh_lib.make_mesh(MeshConfig(data=n_dev, model=1))
    batch = (32 if quick else 64) * n_dev
    imgs, labels = synthetic.make_image_dataset(batch, seed=3)
    x, y = mesh_lib.shard_batch(mesh, (jnp.asarray(imgs), jnp.asarray(labels)))
    model = cifar.cifar_cnn()
    opt = zoo.make_optimizer(0.05)

    variants = [
        ("psum", CommConfig(impl="psum")),
        ("ring", CommConfig(impl="ring")),
        ("ring_bf16", CommConfig(impl="ring", wire_dtype="bfloat16")),
    ]
    rows: List[Row] = []
    losses = {}
    for name, comm in variants:
        st = zoo.init_state(model, jax.random.key(0), cifar.IN_SHAPE, opt)
        step = zoo.make_train_step(
            model, opt, accum_steps=2, mesh=mesh, comm=comm
        )
        # Parity probe: 3 steps from identical init, BEFORE the timed
        # region mutates state (the timed thunk chains its own states).
        pst, ploss = st, None
        for _ in range(3):
            pst, ploss = step(pst, x, y)
        losses[name] = float(ploss)

        def thunk(carry, step=step, x=x, y=y):
            # step donates its state arg, so a captured init state would
            # be deleted after the first call — rebuild on each restart
            # (thunk(None) runs before _sync_time's timed region).
            s = carry[0] if carry is not None else zoo.init_state(
                model, jax.random.key(0), cifar.IN_SHAPE, opt
            )
            return step(s, x, y)

        ips, ips_range, n_s = _sampled_ips(
            thunk, repeats=10 if quick else 30, images_per_call=batch
        )
        dloss = losses[name] - losses["psum"]
        rows.append(
            Row(f"comm_{name}_accum_mesh_train", ips, "images/sec",
                baseline=None,
                baseline_src=(f"{n_dev}dev b{batch} accum2; "
                              f"loss-psum={dloss:+.2e}"),
                value_range=ips_range, value_samples=n_s).finish()
        )

    # --- Hierarchical leg: same devices re-folded as 2 emulated hosts ---
    if n_dev >= 4 and n_dev % 2 == 0:
        hmesh = mesh_lib.make_hier_mesh(n_hosts=2)
        hx, hy = mesh_lib.shard_batch(
            hmesh, (jnp.asarray(imgs), jnp.asarray(labels))
        )
        hier_variants = [
            ("psum_hier", CommConfig(impl="psum")),
            ("hier", CommConfig(impl="hierarchical", hosts=2)),
            ("hier_bf16",
             CommConfig(impl="hierarchical", wire_dtype="bfloat16", hosts=2)),
        ]
        for name, comm in hier_variants:
            st = zoo.init_state(model, jax.random.key(0), cifar.IN_SHAPE, opt)
            step = zoo.make_train_step(
                model, opt, accum_steps=2, mesh=hmesh, comm=comm
            )
            pst, ploss = st, None
            for _ in range(3):
                pst, ploss = step(pst, hx, hy)
            losses[name] = float(ploss)

            def thunk(carry, step=step, hx=hx, hy=hy):
                s = carry[0] if carry is not None else zoo.init_state(
                    model, jax.random.key(0), cifar.IN_SHAPE, opt
                )
                return step(s, hx, hy)

            ips, ips_range, n_s = _sampled_ips(
                thunk, repeats=10 if quick else 30, images_per_call=batch
            )
            dloss = losses[name] - losses["psum_hier"]
            rows.append(
                Row(f"comm_{name}_accum_mesh_train", ips, "images/sec",
                    baseline=None,
                    baseline_src=(f"2host x{n_dev // 2}dev b{batch} accum2; "
                                  f"loss-psum_hier={dloss:+.2e}"),
                    value_range=ips_range, value_samples=n_s).finish()
            )

    # --- ZeRO leg: replicated fused step (ZeRO-2) vs resident shards with
    # just-in-time f32 param gathers (ZeRO-3), same ring comm/batch/lr ---
    zcomm = CommConfig(impl="ring")
    zero_ips = {}
    zero_losses = {}
    for name, zero in (("zero2_ring", 2), ("zero3_ring", 3)):
        if zero == 2:
            fused = FusedStepConfig(update=True, tail=True)
            st0, n_buckets = zoo.init_fused_state(
                model, jax.random.key(0), cifar.IN_SHAPE, n_data=n_dev,
                fused=fused, bucket_bytes=zcomm.bucket_bytes,
            )
            step = zoo.make_fused_train_step(
                model, lr=0.05, momentum=0.9, accum_steps=2, mesh=mesh,
                augment=None, comm=zcomm, fused=fused, n_buckets=n_buckets,
            )

            def init_st():
                return zoo.init_fused_state(
                    model, jax.random.key(0), cifar.IN_SHAPE, n_data=n_dev,
                    fused=FusedStepConfig(update=True, tail=True),
                    bucket_bytes=zcomm.bucket_bytes,
                )[0]

        else:
            fused = FusedStepConfig(update=True, tail=True, zero=3)
            st0, plan = zoo.init_zero3_state(
                model, jax.random.key(0), cifar.IN_SHAPE, n_data=n_dev,
                fused=fused, bucket_bytes=zcomm.bucket_bytes,
            )
            step = zoo.make_zero3_train_step(
                model, lr=0.05, momentum=0.9, accum_steps=2, mesh=mesh,
                augment=None, comm=zcomm, fused=fused, plan=plan,
            )

            def init_st(fused=fused):
                return zoo.init_zero3_state(
                    model, jax.random.key(0), cifar.IN_SHAPE, n_data=n_dev,
                    fused=fused, bucket_bytes=zcomm.bucket_bytes,
                )[0]

        pst, ploss = st0, None
        for _ in range(3):
            pst, ploss = step(pst, x, y)
        zero_losses[name] = float(ploss)

        def thunk(carry, step=step, init_st=init_st):
            s = carry[0] if carry is not None else init_st()
            return step(s, x, y)

        ips, ips_range, n_s = _sampled_ips(
            thunk, repeats=10 if quick else 30, images_per_call=batch
        )
        zero_ips[name] = ips
        if zero == 2:
            src = f"{n_dev}dev b{batch} accum2 fused"
        else:
            dloss = zero_losses[name] - zero_losses["zero2_ring"]
            ratio = ips / zero_ips["zero2_ring"]
            src = (f"{n_dev}dev b{batch} accum2 fused; "
                   f"loss-zero2={dloss:+.2e}; ips/zero2={ratio:.3f}x")
        rows.append(
            Row(f"comm_{name}_accum_mesh_train", ips, "images/sec",
                baseline=None, baseline_src=src,
                value_range=ips_range, value_samples=n_s).finish()
        )

    rows.extend(_bench_async_ablation())
    return rows


def _bench_async_ablation() -> List[Row]:
    """Sync ring vs stale-S vs EASGD under a seeded 400 ms straggler —
    the virtual-clock leg behind the ASYNC_GATE contract line (see the
    bench_comm docstring for the gate terms)."""
    import numpy as np

    from parallel_cnn_tpu.config import AsyncConfig
    from parallel_cnn_tpu.models import lenet_ref
    from parallel_cnn_tpu.resilience.chaos import ChaosMonkey
    from parallel_cnn_tpu.train import async_dp

    W, b, dt, step_ms, horizon = 4, 8, 0.05, 100.0, 1600.0
    params = lenet_ref.init(jax.random.key(7))
    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.uniform(0, 1, (W, b, 28, 28)).astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, (W, b)).astype(np.int32))
    ex, ey = xs.reshape(W * b, 28, 28), ys.reshape(W * b)

    modes = {
        "sync_ring": AsyncConfig(mode="off", workers=W),
        "stale2": AsyncConfig(mode="stale", staleness_bound=2, workers=W),
        "easgd": AsyncConfig(mode="easgd", easgd_period=4, easgd_rho=0.5,
                             workers=W),
    }
    rows: List[Row] = []
    ratios = {}
    max_stale = 0
    for name, acfg in modes.items():
        clean = async_dp.run_async(
            params, xs, ys, cfg=acfg, dt=dt, step_ms=step_ms,
            horizon_ms=horizon,
        )
        chaos = async_dp.run_async(
            params, xs, ys, cfg=acfg, dt=dt, step_ms=step_ms,
            horizon_ms=horizon, chaos=ChaosMonkey.from_spec("slow-worker@2:400"),
        )
        ratios[name] = chaos.throughput() / clean.throughput()
        max_stale = max(max_stale, clean.ledger.max_staleness(),
                        chaos.ledger.max_staleness())
        # Virtual img/s: microbatches × b per virtual second — exact and
        # deterministic (no wall clock anywhere in the harness).
        rows.append(
            Row(f"async_{name}_virtual", round(
                clean.throughput() * b * 1000.0, 1), "images/virtual-sec",
                baseline=None,
                baseline_src=(
                    f"{W} workers b{b} S=2 horizon {horizon:.0f}ms; "
                    f"under slow-worker@2:400: {ratios[name]:.3f}x clean"
                )).finish()
        )

    # Seeded 3-step loss deltas vs the sync ring.  EASGD-under-chaos is
    # NOT gated at 1e-2: the straggler reorders the elastic rounds, which
    # genuinely changes the center trajectory (docs/fault_tolerance.md's
    # "not preserved" list) — it is reported and sanity-bounded instead.
    sync3 = async_dp.run_async(
        params, xs, ys, cfg=modes["sync_ring"], dt=dt, step_ms=step_ms,
        max_server_steps=3,
    )
    loss_sync = float(async_dp.eval_err(sync3.params, ex, ey))
    deltas = {}
    loss_cfgs = {
        "stale_clean": (modes["stale2"], None),
        "stale_chaos": (modes["stale2"], "slow-worker@2:400"),
        "easgd_clean": (AsyncConfig(mode="easgd", easgd_period=1,
                                    easgd_rho=0.9, workers=W), None),
        "easgd_chaos": (AsyncConfig(mode="easgd", easgd_period=1,
                                    easgd_rho=0.9, workers=W),
                        "slow-worker@2:400"),
    }
    for name, (acfg, spec) in loss_cfgs.items():
        r = async_dp.run_async(
            params, xs, ys, cfg=acfg, dt=dt, step_ms=step_ms,
            max_server_steps=3,
            chaos=ChaosMonkey.from_spec(spec) if spec else None,
        )
        deltas[name] = abs(loss_sync - float(async_dp.eval_err(
            r.params, ex, ey)))
        rows.append(
            Row(f"async_loss_delta_{name}", round(deltas[name], 6),
                "|loss - sync| after 3 steps",
                baseline=None,
                baseline_src=("gate <= 1e-2" if name != "easgd_chaos"
                              else "reported; sanity bound 1e-1")).finish()
        )

    gate_ok = (
        ratios["stale2"] >= 0.8
        and ratios["easgd"] >= 0.8
        and ratios["sync_ring"] < 0.8      # anti-vacuity: sync DID stall
        and deltas["stale_clean"] <= 1e-2
        and deltas["stale_chaos"] <= 1e-2
        and deltas["easgd_clean"] <= 1e-2
        and deltas["easgd_chaos"] <= 1e-1
        and max_stale <= 2
    )
    if not gate_ok:
        rows.append(Row(
            "error_async_gate", -1.0, "error",
            baseline_src=(
                f"ratios sync {ratios['sync_ring']:.3f} (< 0.8 wanted), "
                f"stale {ratios['stale2']:.3f}, easgd {ratios['easgd']:.3f} "
                f"(>= 0.8 wanted); deltas {deltas}; max staleness "
                f"{max_stale} (<= 2)"
            ),
        ))
    print(
        f"ASYNC_GATE {'PASS' if gate_ok else 'FAIL'}: straggler ratios "
        f"sync {ratios['sync_ring']:.3f} < 0.8 <= stale "
        f"{ratios['stale2']:.3f} / easgd {ratios['easgd']:.3f}, 3-step "
        f"|dloss| stale {deltas['stale_chaos']:.2e} easgd "
        f"{deltas['easgd_clean']:.2e} (<= 1e-2), max staleness "
        f"{max_stale} <= S=2",
        flush=True,
    )
    return rows


def bench_fused(quick: bool) -> List[Row]:
    """Fused-training-step ablation (round 7), two legs.

    LeNet leg (single-device): `batched_step` vs `fused_batched_step` —
    the same local_grad_sums engine with the tree-wide `p += dt·g` pass
    replaced by one ops.pallas_update kernel per gradient bucket; the
    fused row's baseline_src carries the final-err delta (f32 — the two
    are the same math).

    Zoo leg (accum×mesh, all devices on the data axis): the comm-suite
    step body with the fused pieces layered on —

      unfused      ring RS/AG + optax (the bench_comm "ring" variant),
      fused_tail   + the fused pool→FC→softmax-CE loss tail,
      fused_upd    + update-on-arrival: per-bucket fused SGD/momentum on
                   the reduce-scattered shards, param all-gather (f32),
                   no post-barrier optimizer pass,
      fused_bf16   + bf16 activations over f32 masters with dynamic loss
                   scaling.

    Every row's baseline_src carries its 3-step-loss delta vs unfused —
    the ≤1e-5 (f32) / ≤1e-2 (bf16) parity contract rides in the table,
    like --suite comm. On the CPU harness the tail runs its XLA twin
    (same math as the Mosaic kernel; tests pin the two ≤1e-5) and
    "ICI" is shared-memory copies — ranking is indicative, the TPU run
    is the real evidence."""
    from parallel_cnn_tpu.config import CommConfig, FusedStepConfig, MeshConfig
    from parallel_cnn_tpu.data import synthetic
    from parallel_cnn_tpu.nn import cifar
    from parallel_cnn_tpu.train import step as step_lib, zoo
    from parallel_cnn_tpu.parallel import mesh as mesh_lib

    rows: List[Row] = []

    # --- LeNet leg: fused bucket update on the reference grad engine ---
    from parallel_cnn_tpu.models import lenet_ref

    lb = 256 if quick else 512
    limgs, llabels = synthetic.make_dataset(lb, seed=4)
    lx, ly = jnp.asarray(limgs), jnp.asarray(llabels)
    lerrs = {}
    for name, fused in (("unfused", False), ("fused", True)):
        lstep = step_lib.batched_step_fn("reference", fused=fused)
        p, err = lenet_ref.init(jax.random.key(0)), None
        for _ in range(3):
            p, err = lstep(p, lx, ly, 0.01)
        lerrs[name] = float(err)

        def lthunk(carry, lstep=lstep):
            p = carry[0] if carry is not None else lenet_ref.init(
                jax.random.key(0)
            )
            return lstep(p, lx, ly, 0.01)

        ips, ips_range, n_s = _sampled_ips(
            lthunk, repeats=10 if quick else 30, images_per_call=lb
        )
        derr = lerrs[name] - lerrs["unfused"]
        rows.append(
            Row(f"fused_lenet_{name}_batched_step", ips, "images/sec",
                baseline=None,
                baseline_src=f"b{lb} dt.01; err-unfused={derr:+.2e}",
                value_range=ips_range, value_samples=n_s).finish()
        )

    # --- Zoo leg: tail / update-on-arrival / bf16 on the mesh ---
    n_dev = len(jax.devices())
    if n_dev < 2:
        return rows
    mesh = mesh_lib.make_mesh(MeshConfig(data=n_dev, model=1))
    batch = (32 if quick else 64) * n_dev
    imgs, labels = synthetic.make_image_dataset(batch, seed=3)
    x, y = mesh_lib.shard_batch(mesh, (jnp.asarray(imgs), jnp.asarray(labels)))
    model = cifar.cifar_cnn()
    comm = CommConfig(impl="ring")
    # Gentle lr: the in-row parity probe is a numerics contract, checked
    # in a numerically sane regime (the dryrun comm leg's rationale — at
    # aggressive lr the 3-step loss inflates and bf16 activation roundoff
    # rides past the documented 1e-2 bound; observed 1.34e-2 at lr=0.05).
    # Throughput is lr-independent, so the timed rows lose nothing.
    lr, momentum = 0.01, 0.9

    variants = [
        ("unfused", None),
        ("fused_tail",
         FusedStepConfig(update=False, tail=True, act_dtype="float32")),
        ("fused_upd",
         FusedStepConfig(update=True, tail=True, act_dtype="float32")),
        ("fused_bf16",
         FusedStepConfig(update=True, tail=True, act_dtype="bfloat16")),
    ]
    losses = {}
    for name, fused in variants:
        if fused is not None and fused.update:
            st0, n_buckets = zoo.init_fused_state(
                model, jax.random.key(0), cifar.IN_SHAPE, n_data=n_dev,
                fused=fused, bucket_bytes=comm.bucket_bytes,
            )
            step = zoo.make_fused_train_step(
                model, lr=lr, momentum=momentum, accum_steps=2, mesh=mesh,
                augment=None, comm=comm, fused=fused, n_buckets=n_buckets,
            )

            def init_st(fused=fused):
                return zoo.init_fused_state(
                    model, jax.random.key(0), cifar.IN_SHAPE, n_data=n_dev,
                    fused=fused, bucket_bytes=comm.bucket_bytes,
                )[0]

        else:
            opt = zoo.make_optimizer(lr, momentum=momentum)
            st0 = zoo.init_state(model, jax.random.key(0), cifar.IN_SHAPE,
                                 opt)
            step = zoo.make_train_step(
                model, opt, accum_steps=2, mesh=mesh, comm=comm, fused=fused
            )

            def init_st(opt=opt):
                return zoo.init_state(
                    model, jax.random.key(0), cifar.IN_SHAPE, opt
                )

        # Parity probe: 3 steps from identical init, BEFORE the timed
        # region mutates state (same discipline as bench_comm).
        pst, ploss = st0, None
        for _ in range(3):
            pst, ploss = step(pst, x, y)
        losses[name] = float(ploss)

        def thunk(carry, step=step, init_st=init_st):
            s = carry[0] if carry is not None else init_st()
            return step(s, x, y)

        ips, ips_range, n_s = _sampled_ips(
            thunk, repeats=10 if quick else 30, images_per_call=batch
        )
        dloss = losses[name] - losses["unfused"]
        rows.append(
            Row(f"fused_zoo_{name}_accum_mesh_train", ips, "images/sec",
                baseline=None,
                baseline_src=(f"{n_dev}dev b{batch} accum2; "
                              f"loss-unfused={dloss:+.2e}"),
                value_range=ips_range, value_samples=n_s).finish()
        )
    return rows


def bench_northstar(quick: bool) -> List[Row]:
    """BASELINE.json's north-star metric: epochs-to-98% test accuracy for
    the MNIST LeNet (throughput mode, shuffled minibatch SGD), plus the
    final accuracy. Runs on real MNIST when the idx image files exist;
    the reference snapshot ships labels only (SURVEY.md B15), so the
    deterministic synthetic stand-in is the default — the row name says
    which. (No published reference value exists; accuracy was never
    reported numerically, BASELINE.md.)"""
    from parallel_cnn_tpu.config import Config, DataConfig, TrainConfig
    from parallel_cnn_tpu.data import pipeline
    from parallel_cnn_tpu.train import trainer

    n_train, n_test = (10_000, 2_000) if quick else (60_000, 10_000)
    data_cfg = DataConfig(
        synthetic_train_count=n_train, synthetic_test_count=n_test
    )
    train_ds, test_ds = pipeline.load_train_test(data_cfg)
    # The pipeline tags (and integrity-logs) real idx files; rows label
    # themselves from that tag, so dropping the four files in data/ turns
    # this suite into the real-MNIST evidence automatically (README recipe).
    # BOTH splits must be real: a partial drop (train real, test fallback
    # synthetic) must never label synthetic-test accuracy as mnist evidence.
    both_real = train_ds.source == "mnist" and test_ds.source == "mnist"
    tag = "mnist" if both_real else "synthetic_mnist"
    # synthetic_* counts don't bound real idx files — cap explicitly so
    # --quick stays quick when the full dataset is present.
    train_ds = pipeline.Dataset(
        train_ds.images[:n_train], train_ds.labels[:n_train], train_ds.source
    )
    test_ds = pipeline.Dataset(
        test_ds.images[:n_test], test_ds.labels[:n_test], test_ds.source
    )

    # Two trajectories: strict parity (the reference's per-sample SGD —
    # "parity with Sequential baseline loss curve") and throughput mode
    # (minibatch; dt re-tuned to 0.4 — mean-grads at the per-sample dt=0.1
    # undertrain 32×, dt≥0.8 saturates the sigmoids to chance; full sweep
    # table in docs/dt_sweep.md).
    modes = [
        ("parity", TrainConfig(epochs=1, batch_size=1), 4),
        ("batched", TrainConfig(epochs=1, batch_size=32, dt=0.4,
                                shuffle=True, prefetch="off"), 10),
    ]
    rows = []
    for mode, tc0, max_epochs in modes:
        params = None
        epochs_to_98 = None
        acc = 0.0
        t0 = time.perf_counter()
        for epoch in range(1, max_epochs + 1):
            cfg = Config(data=data_cfg, train=tc0)
            res = trainer.learn(cfg, train_ds, params=params, verbose=False,
                                epoch_offset=epoch - 1)
            params = res.params
            acc = 100.0 - trainer.test(params, test_ds, verbose=False)
            if acc >= 98.0:
                epochs_to_98 = epoch
                break
        wall = time.perf_counter() - t0
        rows.append(
            Row(f"northstar_epochs_to_98pct_{mode}_{tag}",
                float(epochs_to_98 if epochs_to_98 is not None else -1),
                "epochs", None,
                f"acc {acc:.2f}% after {wall:.1f}s "
                "(reference never reports accuracy)").finish()
        )
        rows.append(
            Row(f"northstar_final_accuracy_{mode}_{tag}", round(acc, 2),
                "%", None, "98% target (BASELINE.json)").finish()
        )
    return rows


def bench_zoo(quick: bool) -> List[Row]:
    """Model-zoo step throughput (BASELINE.json configs #3-#5 + round-4
    additions): CIFAR CNN, ResNet-18 and VGG-16 (XLA convs and the
    Pallas conv-kernel backend), and ResNet-50 at ImageNet shape with
    gradient accumulation — on TPU also with every conv (incl. the
    7×7-s2 stem) on the Pallas kernels."""
    from parallel_cnn_tpu.data import synthetic
    from parallel_cnn_tpu.nn import cifar, resnet, vgg
    from parallel_cnn_tpu.train import zoo

    rows = []
    batch = 256 if quick else 512
    imgs, labels = synthetic.make_image_dataset(batch, seed=1)
    x, y = jnp.asarray(imgs), jnp.asarray(labels)
    # Per-case timed repeats: scale inversely with step cost so cheap rows
    # amortize the relay readback RTT (cifar_cnn ~6 ms/step needs many
    # chained steps; ResNet-50 @224² ~0.5 s/step needs few).
    cases = [
        ("cifar_cnn", cifar.cifar_cnn(), cifar.IN_SHAPE, x, y, 1, 50),
        ("resnet18_cifar", resnet.resnet18(10, cifar_stem=True),
         cifar.IN_SHAPE, x, y, 1, 20),
        ("vgg16_cifar", vgg.vgg16(10), cifar.IN_SHAPE, x, y, 1, 10),
    ]
    from parallel_cnn_tpu.utils.backend import canonical_platform

    if canonical_platform() == "tpu" or os.environ.get("PCNN_BENCH_PALLAS"):
        # Compiled Mosaic only: interpret mode at bench batch sizes is
        # minutes/step on CPU (correctness covered by tests/test_pallas_conv).
        cases.append(
            ("resnet18_cifar_pallasconv",
             resnet.resnet18(10, cifar_stem=True, conv_backend="pallas"),
             cifar.IN_SHAPE, x, y, 1, 10)
        )
        cases.append(
            ("vgg16_cifar_pallasconv",
             vgg.vgg16(10, conv_backend="pallas"),
             cifar.IN_SHAPE, x, y, 1, 10)
        )
    # Config #5: ResNet-50 at ImageNet shape (synthetic stand-in — no
    # egress, BASELINE.md), microbatched via grad accumulation so the
    # effective batch exceeds single-chip activation memory. --quick
    # shrinks the spatial dims (224² ResNet-50 is minutes/step on the CPU
    # harness); the full run is the ImageNet-shape number.
    # b256×accum16 (microbatch 16) is the measured-optimal operating
    # point on one v5e: throughput saturates there at ~2450 img/s ≈ 30.8%
    # MFU while b64 leaves ~1.7× of per-step fixed-cost amortization on
    # the table (docs/resnet50_ablate_r6.md, MFU-corrected ablation grid).
    in50 = (64, 64, 3) if quick else (224, 224, 3)
    b50 = 16 if quick else 256
    imgs50, labels50 = synthetic.make_image_dataset(
        b50, hw=in50[:2], classes=100, seed=2
    )
    x50, y50 = jnp.asarray(imgs50), jnp.asarray(labels50)
    cases.append(
        ("resnet50_imagenet_accum16" if not quick else
         "resnet50_imagenet_accum4",
         resnet.resnet50(100, cifar_stem=False),
         in50, x50, y50, 4 if quick else 16, 5)
    )
    if canonical_platform() == "tpu":
        # Round 4: every ResNet-50 conv — 7×7-s2 stem included — on the
        # hand-written kernels ("entire network" at the reference's own
        # framing, PDF Table 8). TPU-only: ~60 Mosaic compiles. Measured
        # at 64×64 input, NOT 224²: the 224² stem kernel alone sat in
        # the remote Mosaic compiler >25 min without finishing (r5,
        # docs/bench_results.md) — a compile-time pathology, not a
        # run-time one — so the full-shape row would eat the suite
        # timeout. The row label carries the shape. Reuse the quick-mode
        # dataset when it already is the 64px one.
        if quick:
            x50p, y50p = x50, y50
        else:
            imgs50p, labels50p = synthetic.make_image_dataset(
                16, hw=(64, 64), classes=100, seed=2
            )
            x50p, y50p = jnp.asarray(imgs50p), jnp.asarray(labels50p)
        cases.append(
            ("resnet50_64px_accum4_pallasconv",
             resnet.resnet50(100, cifar_stem=False, conv_backend="pallas"),
             (64, 64, 3), x50p, y50p, 4, 3)
        )
    for name, model, in_shape, bx, by, accum, reps in cases:
        bsz = bx.shape[0]
        opt = zoo.make_optimizer(0.05)
        st = zoo.init_state(model, jax.random.key(0), in_shape, opt)
        step = zoo.make_train_step(model, opt, accum_steps=accum)

        def thunk(carry, step=step, st=st, bx=bx, by=by):
            s = carry[0] if carry is not None else st
            return step(s, bx, by)

        ips, ips_range, n_s = _sampled_ips(
            thunk, repeats=2 if quick else reps, images_per_call=bsz
        )
        rows.append(
            Row(f"zoo_{name}_train", round(ips, 1), "images/sec",
                value_range=ips_range, value_samples=n_s).finish()
        )
    return rows


def bench_serve(quick: bool) -> List[Row]:
    """Inference-serving ablation (serve/): the SAME engine + weights
    under three serving disciplines —

      batch1      sequential predict(x[None]) per request — the no-
                  batching strawman every serving system is measured
                  against,
      dynamic     one replica behind the dynamic batcher, closed-loop
                  clients (batching emerges from concurrency),
      2replicas   dynamic batching + a second engine replica (only when
                  the platform exposes ≥2 devices; on the 8-virtual-CPU
                  harness the replicas share silicon, so the row shows
                  pipeline overlap, not 2× silicon).

    Throughput rows are wall-clock request rates (host queueing included
    — that IS the serving number, unlike the chained-dispatch training
    rows), median of N with range. Each dynamic row carries client p50/
    p99 and the shed rate in the baseline_src column; at this sub-
    capacity offered load the shed rate must be 0. The parity row
    re-proves the padding contract in-suite: a padded-bucket engine
    prediction must be bit-identical to the same-bucket jit forward."""
    from parallel_cnn_tpu.config import ServeConfig
    from parallel_cnn_tpu.serve import get, loadgen, serve_stack

    handle = get("cifar_cnn")
    max_batch = 8 if quick else 16
    n_req = 96 if quick else 256
    cfg0 = ServeConfig(model="cifar_cnn", max_batch=max_batch,
                       max_wait_ms=2.0, queue_depth=max(n_req, 256))
    samples = loadgen.make_samples(64, handle.in_shape, seed=0)
    rows: List[Row] = []

    # -- parity row first: no point timing a wrong answer ---------------
    pool, batcher = serve_stack(handle, cfg0, start=False)
    e0 = pool.engines[0]
    n, b = 3, 4
    got = e0.predict(samples[:n])
    padded = np.concatenate(
        [samples[:n], np.zeros((b - n, *handle.in_shape), np.float32)]
    )
    ref = np.asarray(jax.jit(
        lambda v: handle.forward(e0._params, e0._state, v)
    )(jnp.asarray(padded)))[:n]
    if not np.array_equal(got, ref):
        raise RuntimeError(
            "serve parity violated: padded-bucket engine prediction is not "
            f"bit-identical to the same-bucket jit forward "
            f"(max |d| {float(np.max(np.abs(got - ref))):.2e})"
        )
    rows.append(
        Row("serve_parity_padded_bucket", 1.0, "bitwise-equal",
            baseline_src=f"n={n} padded into bucket {b}, cifar_cnn").finish()
    )
    batcher.close()

    def timed(run_once) -> tuple:
        """Median-of-N wall-clock req/s (+ the last run's report)."""
        rps, last = [], None
        for _ in range(_n_samples()):
            t0 = time.perf_counter()
            last = run_once()
            rps.append(round(n_req / (time.perf_counter() - t0), 1))
        return _median(rps), [min(rps), max(rps)], len(rps), last

    # -- batch=1 sequential strawman ------------------------------------
    e0.predict(samples[:1])  # warm bucket 1

    def run_batch1():
        for i in range(n_req):
            e0.predict(samples[i % len(samples)][None])
        return None

    v, rng_, n_s, _ = timed(run_batch1)
    rows.append(
        Row("serve_batch1_sequential", v, "req/sec",
            baseline_src="no batching: one predict per request",
            value_range=rng_, value_samples=n_s).finish()
    )
    batch1_rps = v

    # -- dynamic batching (1 replica, then 2 if the platform has them) --
    n_dev = len(jax.devices())
    variants = [("serve_dynamic_batch", 1)]
    if n_dev >= 2:
        variants.append(("serve_dynamic_2replicas", 2))
    else:
        print("[bench_serve] 2-replica row skipped (1 device visible; "
              "run under the 8-virtual-device CPU harness or on a multi-"
              "chip platform)", flush=True)
    for name, n_rep in variants:
        cfg = ServeConfig(model="cifar_cnn", max_batch=max_batch,
                          max_wait_ms=2.0, queue_depth=max(n_req, 256),
                          n_replicas=n_rep)
        pool, batcher = serve_stack(handle, cfg)
        try:
            def run_closed(batcher=batcher):
                return loadgen.run(
                    batcher, pattern="closed", n_requests=n_req,
                    concurrency=16, samples=samples, seed=0,
                )

            v, rng_, n_s, rep = timed(run_closed)
            lat = rep.latency.summary(scale=1e3)
            rows.append(
                Row(name, v, "req/sec",
                    baseline=batch1_rps, baseline_src=(
                        f"vs batch1; p50 {lat['p50']:.1f} ms, "
                        f"p99 {lat['p99']:.1f} ms, "
                        f"shed {rep.shed_rate:.3f}, "
                        f"occupancy {batcher.stats.mean_occupancy():.2f}"
                    ),
                    value_range=rng_, value_samples=n_s).finish()
            )
            if rep.shed_rate != 0.0:
                raise RuntimeError(
                    f"{name}: shed rate {rep.shed_rate:.3f} at sub-capacity "
                    "offered load (closed loop must never shed with "
                    "queue_depth >= n_requests)"
                )
        finally:
            batcher.close()

    rows.extend(_bench_serve_slo(quick))
    return rows


def _bench_serve_slo(quick: bool) -> List[Row]:
    """The SLO scenario sweep behind the SERVE_SLO_GATE contract line.

    Five seeded scenarios (serve/scenarios.py) against a lenet_ref
    stack with admission control on, judged by their explicit p99 /
    shed-rate / conservation gates:

      clean legs    diurnal, flash-crowd, slow-client, chaos-kill must
                    PASS their gates,
      trip leg      chaos-slow arms slow-replica@3:400 against a 150 ms
                    p99 gate — the leg passes iff the gate FAILS (the
                    anti-vacuity proof that a tripped SLO is visible),
      autoscaler    flash-crowd on a 1→2-replica pool under the control
                    loop: unrecovered shed rate must land at 0 with at
                    most one scale direction change (no flapping).

    Every leg re-checks the conservation law server-side. Any violated
    expectation appends an error row (rc 1) and flips the gate line to
    SERVE_SLO_GATE FAIL — the serve-chaos playbook mode greps for it."""
    del quick  # scenarios are fixed-duration; quick and full match
    from parallel_cnn_tpu.config import ServeConfig
    from parallel_cnn_tpu.resilience.chaos import ChaosMonkey
    from parallel_cnn_tpu.serve import AutoScaler, get, scenarios, serve_stack

    handle = get("lenet_ref")

    def cfg(**kw):
        base = dict(model="lenet_ref", max_batch=8, max_wait_ms=2.0,
                    queue_depth=256, admission=True, slo_ms=200.0,
                    window_s=2.0)
        base.update(kw)
        return ServeConfig(**base)

    rows: List[Row] = []
    failures: List[str] = []

    def judge(leg: str, rep, want_pass: bool) -> None:
        p99 = rep.p99_ms
        rows.append(Row(
            f"serve_slo_{leg}", round(p99, 2) if p99 is not None else -1.0,
            "ms p99",
            baseline_src=(
                f"gate {rep.p99_gate_ms:.0f} ms, shed {rep.shed_rate:.3f} "
                f"(gate {rep.shed_gate:.2f}), "
                f"{'expected-trip' if not want_pass else 'clean'}, "
                f"gates {rep.gates()}"
            ),
        ).finish())
        if not rep.gates()["conservation"]:
            failures.append(f"{leg}: conservation violated {rep.server}")
        elif want_pass and not rep.passed:
            failures.append(f"{leg}: gates {rep.gates()}")
        elif not want_pass and rep.gates()["p99"]:
            failures.append(
                f"{leg}: p99 gate PASSED under an armed slow-replica "
                "stall — the gate is vacuous"
            )

    # -- clean legs ------------------------------------------------------
    pool, batcher = serve_stack(handle, cfg())
    try:
        judge("diurnal", scenarios.run("diurnal", batcher, seed=0), True)
        judge("flash_crowd",
              scenarios.run("flash-crowd", batcher, seed=1), True)
        judge("slow_client",
              scenarios.run("slow-client", batcher, seed=2), True)
    finally:
        batcher.close()

    # -- chaos legs (fresh stacks: one-shot faults, clean counters) ------
    n_rep = 2 if len(jax.devices()) >= 2 else 1
    pool, batcher = serve_stack(
        handle, cfg(n_replicas=n_rep, max_wait_ms=1.0),
        chaos=ChaosMonkey.from_spec("kill-replica@5"),
    )
    try:
        judge("chaos_kill", scenarios.run("chaos-kill", batcher, seed=3),
              True)
    finally:
        batcher.close()

    pool, batcher = serve_stack(
        handle, cfg(max_wait_ms=1.0),
        chaos=ChaosMonkey.from_spec("slow-replica@3:400"),
    )
    try:
        judge("chaos_slow_trip",
              scenarios.run("chaos-slow", batcher, seed=2), False)
        if not batcher.chaos.slow_replica_fired:
            failures.append("chaos_slow_trip: the stall never injected")
    finally:
        batcher.close()

    # -- autoscaler recovery: flash-crowd must end with 0 unrecovered ----
    # A CPU-fast stack absorbs the crowd without ever needing a second
    # replica, which would leave the scale-up path untested — so a
    # slow-replica stall is armed to push the windowed p99 over the SLO
    # deterministically: the loop MUST scale up, and the crowd must
    # still end with zero unrecovered demand and no flapping. The queue
    # is deep enough to hold the whole crowd through the stall (and
    # admission is off), so the backlog waits instead of shedding —
    # recovery is the second replica draining it.
    pool, batcher = serve_stack(
        handle, cfg(window_s=1.0, admission=False, queue_depth=2048),
        chaos=ChaosMonkey.from_spec("slow-replica@3:400"),
    )
    scaler = AutoScaler(pool, batcher, min_replicas=1, max_replicas=2,
                        slo_ms=200.0, hysteresis=2, cooldown_s=1.0,
                        interval_s=0.05)
    try:
        with scaler:
            rep = scenarios.run("flash-crowd", batcher, seed=7)
        flaps = scaler.direction_changes()
        snap = scaler.snapshot()
        rows.append(Row(
            "serve_slo_autoscaler_flash_crowd",
            round(rep.shed_rate, 4), "unrecovered shed rate",
            baseline_src=(
                f"scale_ups {snap['scale_ups']}, "
                f"scale_downs {snap['scale_downs']}, "
                f"direction changes {flaps} (<= 1), "
                f"routable {snap['routable']}"
            ),
        ).finish())
        if not rep.conservation_ok:
            failures.append(f"autoscaler: conservation {rep.server}")
        if rep.shed_rate != 0.0:
            failures.append(
                f"autoscaler: unrecovered shed rate {rep.shed_rate:.4f} "
                "after flash-crowd (scale-up did not recover demand)"
            )
        if snap["scale_ups"] < 1:
            failures.append(
                "autoscaler: no scale-up despite the armed straggler "
                "pushing windowed p99 over the SLO"
            )
        if flaps > 1:
            failures.append(f"autoscaler: {flaps} direction changes (flap)")
    finally:
        batcher.close()

    if failures:
        rows.append(Row(
            "error_serve_slo_gate", -1.0, "error",
            baseline_src="; ".join(failures),
        ))
    print(
        "SERVE_SLO_GATE "
        + ("PASS: 4 clean scenario legs, chaos-slow trip proven, "
           "autoscaler recovery flap-free"
           if not failures else "FAIL: " + "; ".join(failures)),
        flush=True,
    )
    return rows


def bench_net(quick: bool) -> List[Row]:
    """--suite net: the network front door behind SERVE_NET_GATE.

    Four measured rows plus the scenario sweep (serve/net.py,
    serve/supervisor.py — docs/serving.md "Network front door"):

      cold start      serve_stack seconds with the persistent AOT disk
                      cache empty vs populated; the warm start must
                      issue ZERO compiles (EngineStats-asserted — the
                      issue's acceptance line, not just a timing),
      wire overhead   closed-loop throughput over a loopback socket as
                      a fraction of the same batcher driven in-process,
      hot swap        seconds for the grow→drain→retire weight roll
                      under live socket traffic, failed_delta must be 0,
      scenarios       net-steady / net-slow-loris (must actually reap) /
                      net-kill-endpoint (supervised respawn, retries
                      ride through) judged by their gates, plus the
                      anti-vacuity control arm: the same kill with the
                      supervisor disabled must FAIL its gates.

    Any violated expectation appends an error row (rc 1) and flips the
    contract line to SERVE_NET_GATE FAIL — playbook.sh's net mode greps
    for it."""
    import tempfile

    from parallel_cnn_tpu.config import ServeConfig
    from parallel_cnn_tpu.resilience.chaos import ChaosMonkey
    from parallel_cnn_tpu.resilience.retry import RetryPolicy
    from parallel_cnn_tpu.serve import (
        NetServer, Supervisor, WireStats, get, loadgen, scenarios,
        serve_stack,
    )
    from parallel_cnn_tpu.serve.engine import load_or_init

    handle = get("lenet_ref")

    def cfg(**kw):
        base = dict(model="lenet_ref", max_batch=8, max_wait_ms=2.0,
                    queue_depth=256)
        base.update(kw)
        return ServeConfig(**base)

    rows: List[Row] = []
    failures: List[str] = []

    # -- cold start: AOT disk cache cold vs warm -------------------------
    with tempfile.TemporaryDirectory(prefix="pcnn_aot_bench_") as cdir:
        t0 = time.perf_counter()
        pool, batcher = serve_stack(handle, cfg(), cache_dir=cdir)
        cold_s = time.perf_counter() - t0
        n_entries = sum(e.stats.aot_cache_misses for e in pool.engines)
        cold_compiles = sum(e.stats.aot_compiles for e in pool.engines)
        batcher.close()
        t0 = time.perf_counter()
        pool, batcher = serve_stack(handle, cfg(), cache_dir=cdir)
        warm_s = time.perf_counter() - t0
        warm_compiles = sum(e.stats.aot_compiles for e in pool.engines)
        warm_hits = sum(e.stats.aot_cache_hits for e in pool.engines)
        batcher.close()
    rows.append(Row(
        "net_cold_start_cache_cold", round(cold_s, 3), "sec",
        baseline_src=f"{cold_compiles} compiles, {n_entries} entries "
                     f"written",
    ).finish())
    rows.append(Row(
        "net_cold_start_cache_warm", round(warm_s, 3), "sec",
        baseline=round(cold_s, 3),
        baseline_src=f"cold start above; {warm_compiles} compiles, "
                     f"{warm_hits} disk hits",
    ).finish())
    if cold_compiles == 0 or n_entries == 0:
        failures.append("cold start issued no compiles / wrote no cache "
                        "entries (the cold leg is vacuous)")
    if warm_compiles != 0:
        failures.append(
            f"warm cold-start issued {warm_compiles} compiles "
            "(the acceptance line is ZERO: every bucket must "
            "deserialize from the disk tier)"
        )
    if warm_hits != n_entries:
        failures.append(
            f"warm start hit {warm_hits}/{n_entries} disk entries"
        )

    # -- one long-lived stack for the wire legs --------------------------
    pool, batcher = serve_stack(handle, cfg())
    try:
        samples = scenarios.make_samples(32, handle.in_shape, seed=0)
        n_req = 96 if quick else 256

        # In-process closed loop vs the identical loop over loopback.
        inproc = loadgen.run_closed_loop(
            batcher, samples, n_requests=n_req, concurrency=4, seed=0,
        )
        wire = WireStats()
        srv = NetServer(batcher, wire=wire, conn_deadline_ms=5000.0).start()
        try:
            netrep = loadgen.run_closed_loop_net(
                srv.address, samples, n_requests=n_req, concurrency=4,
                timeout_s=15.0, seed=0,
            )
        finally:
            srv.close()
        ratio = (netrep.throughput / inproc.throughput
                 if inproc.throughput > 0 else 0.0)
        rows.append(Row(
            "net_wire_throughput_ratio", round(ratio, 3),
            "x of in-process",
            baseline_src=(
                f"wire {netrep.throughput:.0f} req/s vs in-process "
                f"{inproc.throughput:.0f} req/s, {n_req} requests x 4 "
                f"clients, NDJSON over loopback"
            ),
        ).finish())
        if netrep.completed != n_req or inproc.completed != n_req:
            failures.append(
                f"throughput legs dropped requests (wire "
                f"{netrep.completed}/{n_req}, in-process "
                f"{inproc.completed}/{n_req})"
            )
        if not wire.balanced():
            failures.append(f"throughput leg wire ledger {wire.snapshot()}")

        # -- scenario legs ----------------------------------------------
        def judge(leg, rep, want_pass=True):
            p99 = rep.p99_ms
            rows.append(Row(
                f"net_{leg}", round(p99, 2) if p99 is not None else -1.0,
                "ms p99",
                baseline_src=(
                    f"{'expected-trip' if not want_pass else 'clean'}, "
                    f"gates {rep.gates()}, wire {rep.wire}"
                ),
            ).finish())
            if not rep.wire_ok:
                failures.append(f"{leg}: wire ledger broken {rep.wire}")
            elif want_pass and not rep.passed:
                failures.append(f"{leg}: gates {rep.gates()}")
            elif not want_pass and rep.passed:
                failures.append(
                    f"{leg}: PASSED with the supervisor disabled under an "
                    "armed kill-endpoint — the respawn gate is vacuous"
                )
            return rep

        # Clean steady state.
        wire = WireStats()
        srv = NetServer(batcher, wire=wire, conn_deadline_ms=5000.0).start()
        try:
            judge("steady", scenarios.run_net(
                "net-steady", batcher, wire=wire, server=srv, seed=0,
            ))
        finally:
            srv.close()

        # Slow loris: the stalled socket must be reaped as expired.
        wire = WireStats()
        srv = NetServer(batcher, wire=wire, conn_deadline_ms=150.0).start()
        try:
            rep = judge("slow_loris", scenarios.run_net(
                "net-slow-loris", batcher, wire=wire, server=srv,
                chaos=ChaosMonkey.from_spec("slow-loris@3:400"), seed=1,
            ))
            if rep.wire.get("reaped", 0) < 1:
                failures.append("slow_loris: the stall never reaped")
        finally:
            srv.close()

        # Supervised kill: retries ride through the respawn.
        wire = WireStats()
        armed = [ChaosMonkey.from_spec("kill-endpoint@12")]

        def factory(port, seq_start):
            m = armed.pop(0) if armed else None
            return NetServer(batcher, port=port, conn_deadline_ms=2000.0,
                             wire=wire, chaos=m, seq_start=seq_start,
                             ).start()

        sup = Supervisor(factory, policy=RetryPolicy(
            attempts=6, base_delay=0.02, max_delay=0.2, seed=0,
        )).start()
        try:
            rep = judge("kill_endpoint_supervised", scenarios.run_net(
                "net-kill-endpoint", batcher, wire=wire, supervisor=sup,
                retry=RetryPolicy(attempts=8, base_delay=0.05,
                                  max_delay=0.5, seed=1),
            ))
            if sup.respawns < 1 or sup.gave_up:
                failures.append(
                    f"kill_endpoint_supervised: respawns={sup.respawns} "
                    f"gave_up={sup.gave_up}"
                )
        finally:
            sup.close()

        # Control arm: same fault, supervision off — must trip.
        wire = WireStats()
        armed = [ChaosMonkey.from_spec("kill-endpoint@12")]
        sup = Supervisor(factory, enabled=False).start()
        try:
            judge("kill_endpoint_unsupervised_trip", scenarios.run_net(
                "net-kill-endpoint", batcher, wire=wire, supervisor=sup,
                retry=RetryPolicy(attempts=3, base_delay=0.01,
                                  max_delay=0.05, seed=1),
            ), want_pass=False)
        finally:
            sup.close()

        # Hot swap under diurnal load (last: it replaces the weights).
        wire = WireStats()
        srv = NetServer(batcher, wire=wire, conn_deadline_ms=5000.0).start()
        try:
            new_params, new_state = load_or_init(handle, seed=7)
            rep = judge("hot_swap_diurnal", scenarios.run_net(
                "net-hot-swap-diurnal", batcher, wire=wire, server=srv,
                swap_params=new_params, swap_state=new_state, seed=2,
            ))
            swap = rep.swap or {}
            rows.append(Row(
                "net_hot_swap_downtime", round(swap.get("seconds", -1.0), 3),
                "sec",
                baseline_src=(
                    f"failed_delta {swap.get('failed_delta')}, swapped "
                    f"{len(swap.get('swapped', []))}, stuck "
                    f"{swap.get('stuck')} — grow-drain-retire under live "
                    f"socket traffic"
                ),
            ).finish())
        finally:
            srv.close()
    finally:
        batcher.close()

    if failures:
        rows.append(Row(
            "error_serve_net_gate", -1.0, "error",
            baseline_src="; ".join(failures),
        ))
    print(
        "SERVE_NET_GATE "
        + ("PASS: warm cold-start compiled nothing, wire ledger balanced "
           "in every leg, loris reaped, supervised kill rode through, "
           "unsupervised trip proven, hot swap zero-failed"
           if not failures else "FAIL: " + "; ".join(failures)),
        flush=True,
    )
    return rows


def bench_cost(quick: bool) -> List[Row]:
    """--suite cost: the static cost accountant next to measured CPU rows.

    For every zoo entry point the graftcheck cost family traces
    (analysis/cost_model.py), three static rows — jaxpr-counted ICI/DCN
    bytes with the closed-form table value as the baseline column (the
    `check --cost` gate asserts these EQUAL; speedup 1.0 means the model
    is exact), and the peak-HBM accounting — then a timed img/s row of
    the SAME step configuration with the analytic roofline as baseline,
    so the model and the measurement are diffable in one place.  On the
    CPU harness the roofline is aspirational (shared-memory "ICI", no
    MXU); the static byte rows are platform-independent."""
    from parallel_cnn_tpu.analysis import cost_model, jaxpr_rules
    from parallel_cnn_tpu.config import CommConfig, FusedStepConfig, MeshConfig
    from parallel_cnn_tpu.data import synthetic
    from parallel_cnn_tpu.nn import cifar
    from parallel_cnn_tpu.train import zoo
    from parallel_cnn_tpu.parallel import mesh as mesh_lib

    n_dev = len(jax.devices())
    if n_dev < 2:
        return []

    rows: List[Row] = []
    costs = {}
    for name, closed, spec in jaxpr_rules.trace_entry_points(
        fast=False, with_specs=True
    ):
        if spec is None:
            continue
        c = cost_model.entry_costs(name, closed, spec)
        costs[name] = c
        short = name.replace("zoo.", "").replace("_step", "")
        rows.append(
            Row(f"cost_{short}.ici", float(c["bytes_ici"]), "bytes/step/dev",
                baseline=float(c["expected_bytes_ici"]),
                baseline_src="closed-form table, docs/collectives.md").finish()
        )
        if c["bytes_dcn"] or c["expected_bytes_dcn"]:
            rows.append(
                Row(f"cost_{short}.dcn", float(c["bytes_dcn"]),
                    "bytes/step/dev",
                    baseline=float(c["expected_bytes_dcn"]),
                    baseline_src="closed-form table, "
                                 "docs/collectives.md").finish()
            )
        rows.append(
            Row(f"cost_{short}.peak_hbm", float(c["peak_hbm"]), "bytes/dev",
                baseline=None,
                baseline_src=(
                    f"resident+activations+grad shards; transient "
                    f"gather {c['transient_gather_bytes']} B"
                )).finish()
        )

    # --- timed legs: the same configurations the specs describe ---
    batch = 2 * n_dev
    imgs, labels = synthetic.make_image_dataset(batch, seed=3)
    model = cifar.cifar_cnn()
    ring_bf16 = CommConfig(impl="ring", wire_dtype="bfloat16")
    repeats = 5 if quick else 15

    def timed_row(entry, mesh, make_state, step):
        x, y = mesh_lib.shard_batch(
            mesh, (jnp.asarray(imgs), jnp.asarray(labels))
        )
        def thunk(carry, step=step, x=x, y=y):
            s = carry[0] if carry is not None else make_state()
            return step(s, x, y)

        ips, ips_range, n_s = _sampled_ips(
            thunk, repeats=repeats, images_per_call=batch
        )
        c = costs[entry]
        short = entry.replace("zoo.", "").replace("_step", "")
        rows.append(
            Row(f"cost_{short}.img_s", ips, "images/sec",
                baseline=c["roofline_img_s"],
                baseline_src="analytic roofline (cost_report.json)",
                value_range=ips_range, value_samples=n_s).finish()
        )

    mesh = mesh_lib.make_mesh(MeshConfig(data=n_dev, model=1))
    opt = zoo.make_optimizer(0.01, momentum=0.9)
    timed_row(
        "zoo.comm_step.ring_bf16", mesh,
        lambda: zoo.init_state(model, jax.random.key(1),
                               cifar.IN_SHAPE, opt),
        zoo.make_train_step(model, opt, accum_steps=2, mesh=mesh,
                            comm=ring_bf16),
    )
    fused = FusedStepConfig(update=True, tail=True, act_dtype="bfloat16")
    fst, n_buckets = zoo.init_fused_state(
        model, jax.random.key(1), cifar.IN_SHAPE,
        n_data=n_dev, fused=fused, bucket_bytes=ring_bf16.bucket_bytes,
    )
    del fst
    timed_row(
        "zoo.fused_step.ring_bf16", mesh,
        lambda: zoo.init_fused_state(
            model, jax.random.key(1), cifar.IN_SHAPE, n_data=n_dev,
            fused=fused, bucket_bytes=ring_bf16.bucket_bytes,
        )[0],
        zoo.make_fused_train_step(
            model, lr=0.01, momentum=0.9, accum_steps=2, mesh=mesh,
            augment=None, comm=ring_bf16, fused=fused,
            n_buckets=n_buckets,
        ),
    )
    z3 = FusedStepConfig(update=True, tail=True, act_dtype="bfloat16",
                         zero=3)
    zst, zplan = zoo.init_zero3_state(
        model, jax.random.key(1), cifar.IN_SHAPE,
        n_data=n_dev, fused=z3, bucket_bytes=ring_bf16.bucket_bytes,
    )
    del zst
    timed_row(
        "zoo.zero3_step.ring_bf16", mesh,
        lambda: zoo.init_zero3_state(
            model, jax.random.key(1), cifar.IN_SHAPE, n_data=n_dev,
            fused=z3, bucket_bytes=ring_bf16.bucket_bytes,
        )[0],
        zoo.make_zero3_train_step(
            model, lr=0.01, momentum=0.9, accum_steps=2, mesh=mesh,
            augment=None, comm=ring_bf16, fused=z3, plan=zplan,
        ),
    )
    return rows


def bench_obs(quick: bool) -> List[Row]:
    """Observability overhead gate (obs/): the SAME training step timed
    under the default no-op bundle vs a LIVE Tracer + event journal —
    spans around every dispatch, one journal record per step, exactly the
    hot-path hooks trainer/zoo wire when --trace is on.

    Rows come in traced/untraced pairs for the lenet batched step and the
    zoo CIFAR step; each traced row's baseline is its untraced twin, so
    the speedup column IS the overhead ratio. The gate: traced must hold
    >= 0.95x the untraced img/s (host-side spans are microseconds against
    multi-ms steps; losing 5% means someone put work on the step path).
    A violation appends an error-unit row (nonzero exit) and the
    OBS_GATE line flips to FAIL — the playbook greps for it."""
    import tempfile

    from parallel_cnn_tpu import obs as obs_lib
    from parallel_cnn_tpu.config import ObsConfig
    from parallel_cnn_tpu.models import lenet_ref
    from parallel_cnn_tpu.nn import cifar
    from parallel_cnn_tpu.train import step as step_lib, zoo

    obs_dir = tempfile.mkdtemp(prefix="pcnn_bench_obs_")
    rng = np.random.default_rng(0)
    repeats = 3 if quick else 6

    # -- workload 1: lenet batched step ---------------------------------
    lbatch = 1024
    lx = jnp.asarray(rng.uniform(0, 1, (lbatch, 28, 28)).astype(np.float32))
    ly = jnp.asarray(rng.integers(0, 10, (lbatch,)).astype(np.int32))
    lstep = step_lib.batched_step_fn("reference")

    def lenet_thunk(carry, bundle):
        # Fresh init per sample: the step donates its params buffers, so
        # a donated pytree can't seed the next _sync_time sample.
        p = carry[0] if carry is not None else lenet_ref.init(
            jax.random.key(0)
        )
        with bundle.span("bench.dispatch", cat="bench"):
            out = lstep(p, lx, ly, 0.1)
        if bundle.enabled:
            bundle.event("bench_step")
        return out

    # -- workload 2: zoo CIFAR CNN step ---------------------------------
    zbatch = 256
    zx = jnp.asarray(
        rng.uniform(0, 1, (zbatch, *cifar.IN_SHAPE)).astype(np.float32)
    )
    zy = jnp.asarray(rng.integers(0, 10, (zbatch,)).astype(np.int32))
    zopt = zoo.make_optimizer(0.1)
    zmodel = cifar.cifar_cnn()
    zstep = zoo.make_train_step(zmodel, zopt)

    def zoo_thunk(carry, bundle):
        st = carry[0] if carry is not None else zoo.init_state(
            zmodel, jax.random.key(1), cifar.IN_SHAPE, zopt
        )
        with bundle.span("bench.dispatch", cat="bench"):
            out = zstep(st, zx, zy)
        if bundle.enabled:
            bundle.event("bench_step")
        return out

    rows: List[Row] = []
    gate_ok = True
    for name, thunk, per_call in (
        ("lenet_step", lenet_thunk, lbatch),
        ("zoo_step", zoo_thunk, zbatch),
    ):
        bundles = {
            "untraced": obs_lib.NOOP,
            "traced": obs_lib.from_config(
                ObsConfig(trace=True, dir=obs_dir), run=f"bench_{name}"
            ),
        }
        # Interleaved sampling: alternate modes within each sample round
        # so slow host drift (thermal, co-tenant load) hits both sides
        # equally instead of biasing whichever mode ran second.
        samples = {m: [] for m in bundles}
        for _ in range(_n_samples()):
            for mode, bundle in bundles.items():
                sec = _sync_time(
                    lambda c, b=bundle, t=thunk: t(c, b), repeats
                )
                samples[mode].append(round(per_call / sec, 1))
        bundles["traced"].finish()
        ips_by_mode = {m: _median(v) for m, v in samples.items()}
        for mode in ("untraced", "traced"):
            vals = samples[mode]
            rows.append(
                Row(f"obs_{name}_{mode}", ips_by_mode[mode], "images/sec",
                    baseline=(ips_by_mode["untraced"]
                              if mode == "traced" else None),
                    baseline_src=("vs untraced twin (gate >= 0.95x)"
                                  if mode == "traced" else "no-op bundle"),
                    value_range=[min(vals), max(vals)],
                    value_samples=len(vals)).finish()
            )
        ratio = ips_by_mode["traced"] / ips_by_mode["untraced"]
        if ratio < 0.95:
            gate_ok = False
            rows.append(
                Row(f"error_obs_overhead_{name}", -1.0, "error",
                    baseline_src=(
                        f"traced {ips_by_mode['traced']} img/s is "
                        f"{ratio:.3f}x untraced "
                        f"{ips_by_mode['untraced']} (< 0.95x gate)"
                    ))
            )
    print(
        "OBS_GATE PASS" if gate_ok else
        "OBS_GATE FAIL: tracing overhead exceeded the 5% budget",
        flush=True,
    )
    return rows


def bench_elastic(quick: bool) -> List[Row]:
    """--suite elastic: resize downtime + reshard cost for the elastic
    runtime (resilience/elastic.py), gated on the contracts the tests
    pin.

    Rows: the wall-clock cost of one ElasticController.resize (quiesce →
    zero3_full_view snapshot → re-mesh → zero3_from_view reshard) in the
    shrink (8→4) and grow (4→8) directions, the snapshot alone, and the
    post-resize step throughput vs the same world trained from scratch
    (the recompile is paid once; steady-state throughput must be
    unchanged — the resharded state is the same layout a fresh init
    produces).

    The gate (ELASTIC_GATE, the playbook's contract line): an 8→4→8
    resize lap matches the fixed-mesh loss trajectory to ≤ 1e-5 and a
    zero-step reshard round trip is bit-exact. A violation appends an
    error-unit row (nonzero exit) and flips the line to FAIL.

    Needs ≥ 8 devices (the playbook mode forces 8 virtual CPU devices);
    fewer is a labeled error row, not a crash."""
    from parallel_cnn_tpu.config import (
        CommConfig, ElasticConfig, FusedStepConfig, MeshConfig,
    )
    from parallel_cnn_tpu.nn import core as nn_core, layers as nn_layers
    from parallel_cnn_tpu.parallel import mesh as mesh_lib
    from parallel_cnn_tpu.resilience.elastic import ElasticController
    from parallel_cnn_tpu.train import zoo

    if len(jax.devices()) < 8:
        raise RuntimeError(
            f"elastic suite needs >= 8 devices, have {len(jax.devices())} "
            "(run under XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "as benches/playbook.sh elastic does)"
        )

    # The parity preconditions (tests/test_elastic.py pins both): f32
    # activations and a BatchNorm-free model — bf16 rounding and
    # per-shard BN stats are partition-dependent, so either would turn
    # the ≤1e-5 gate into a numerics lottery.
    shape = (8, 8, 3)
    model = nn_core.Sequential([
        nn_layers.Conv2D(4, (3, 3)), nn_layers.ReLU(),
        nn_layers.MaxPool(), nn_layers.Flatten(), nn_layers.Dense(10),
    ])
    fused = FusedStepConfig(update=True, tail=True, act_dtype="float32",
                            zero=3)
    comm = CommConfig(impl="ring", bucket_bytes=2048, overlap=True)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(96, *shape)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, (96,)).astype(np.int32))
    batches = [(x[i * 16:(i + 1) * 16], y[i * 16:(i + 1) * 16])
               for i in range(6)]

    def init8():
        return zoo.init_zero3_state(
            model, jax.random.key(7), shape, n_data=8, fused=fused,
            bucket_bytes=comm.bucket_bytes,
        )

    def make_step(mesh, plan):
        return zoo.make_zero3_train_step(
            model, lr=0.05, momentum=0.9, accum_steps=2, mesh=mesh,
            augment=None, comm=comm, fused=fused, plan=plan,
        )

    def full_view_np(st, plan):
        return jax.tree_util.tree_map(
            np.asarray, zoo.zero3_full_view(st, plan)
        )

    mesh8 = mesh_lib.make_mesh(MeshConfig(data=8, model=1))

    # -- gate: fixed-mesh vs resize-lap loss parity ----------------------
    st, plan = init8()
    step = make_step(mesh8, plan)
    fixed = []
    for bx, by in batches:
        st, loss = step(st, bx, by, None)
        fixed.append(float(loss))

    ctl = ElasticController(ElasticConfig(), world=8)
    st, plan = init8()
    mesh = mesh8
    step = make_step(mesh, plan)
    elastic = []
    resize_ms = {}
    for i, (bx, by) in enumerate(batches):
        if i in (2, 4):
            world = 4 if i == 2 else 8
            jax.block_until_ready(jax.tree_util.tree_leaves(st))
            t0 = time.perf_counter()
            st, plan, mesh, _ = ctl.resize(
                i, world, state=st, plan=plan, comm=comm,
            )
            jax.block_until_ready(jax.tree_util.tree_leaves(st))
            resize_ms[f"{8 if world == 4 else 4}to{world}"] = round(
                (time.perf_counter() - t0) * 1e3, 2
            )
            step = make_step(mesh, plan)
        st, loss = step(st, bx, by, None)
        elastic.append(float(loss))
    lap_delta = max(abs(a - b) for a, b in zip(fixed, elastic))

    # -- gate: pure reshard bit-exactness --------------------------------
    v8 = full_view_np(st, plan)
    st4, plan4 = zoo.zero3_from_view(
        v8, n_data=4, bucket_bytes=comm.bucket_bytes
    )
    v4 = full_view_np(st4, plan4)
    bitexact = all(
        np.array_equal(a, b)
        for a, b in zip(jax.tree_util.tree_leaves(v8),
                        jax.tree_util.tree_leaves(v4))
    )

    # -- timing rows -----------------------------------------------------
    rows: List[Row] = [
        Row(f"elastic_resize_{name}_ms", ms, "ms",
            baseline_src="quiesce + snapshot + re-mesh + reshard, "
                         "blocked end to end").finish()
        for name, ms in sorted(resize_ms.items())
    ]
    snap_st, snap_plan = init8()
    t0 = time.perf_counter()
    jax.block_until_ready(
        jax.tree_util.tree_leaves(zoo.zero3_full_view(snap_st, snap_plan))
    )
    rows.append(Row(
        "elastic_snapshot_ms",
        round((time.perf_counter() - t0) * 1e3, 2), "ms",
        baseline_src="zero3_full_view alone (the quiesce-time cost a "
                     "preemption grace window must cover)",
    ).finish())

    # Post-resize steady state vs from-scratch at the same world: the
    # resharded layout must train at the same rate.
    repeats = 4 if quick else 10
    mesh4 = mesh_lib.make_elastic_mesh(4)
    bx, by = batches[0]

    # Fresh state per sample: the zero3 step donates its input buffers,
    # so a state captured once would be deleted after the first sample's
    # warmup (same convention as bench_obs's per-sample init).
    def fresh_scratch():
        return zoo.init_zero3_state(
            model, jax.random.key(7), shape, n_data=4, fused=fused,
            bucket_bytes=comm.bucket_bytes,
        )[0]

    def fresh_resharded():
        return zoo.zero3_from_view(
            v8, n_data=4, bucket_bytes=comm.bucket_bytes
        )[0]

    scratch_plan = zoo.init_zero3_state(
        model, jax.random.key(7), shape, n_data=4, fused=fused,
        bucket_bytes=comm.bucket_bytes,
    )[1]
    ips = {}
    for name, fresh, pl in (
        ("from_scratch", fresh_scratch, scratch_plan),
        ("post_resize", fresh_resharded, plan4),
    ):
        stp = make_step(mesh4, pl)

        def thunk(carry, stp=stp, fresh=fresh):
            cur = carry[0] if carry is not None else fresh()
            return stp(cur, bx, by, None)

        med, rng_, n = _sampled_ips(thunk, repeats, bx.shape[0])
        ips[name] = med
        rows.append(Row(
            f"elastic_step4_{name}", med, "images/sec",
            baseline=(ips["from_scratch"]
                      if name == "post_resize" else None),
            baseline_src=("vs from-scratch init at world 4"
                          if name == "post_resize" else
                          "fresh world-4 init"),
            value_range=rng_, value_samples=n,
        ).finish())

    gate_ok = lap_delta <= 1e-5 and bitexact
    if not gate_ok:
        rows.append(Row(
            "error_elastic_gate", -1.0, "error",
            baseline_src=(
                f"resize-lap max |dloss| {lap_delta:.3e} (gate 1e-5), "
                f"pure reshard bitexact={bitexact}"
            ),
        ))
    print(
        f"ELASTIC_GATE {'PASS' if gate_ok else 'FAIL'}: 8-4-8 lap "
        f"|dloss| {lap_delta:.2e} (<= 1e-5), pure reshard "
        f"{'bit-exact' if bitexact else 'NOT bit-exact'}",
        flush=True,
    )
    return rows


def render_md(rows: List[Row]) -> str:
    lines = [
        "| benchmark | value | unit | reference baseline | speedup | samples |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        if r.baseline is not None:
            base = f"{r.baseline} ({r.baseline_src})"
        else:
            base = r.baseline_src or "—"
        if r.value_range is not None and r.value_samples > 1:
            samples = (f"median of {r.value_samples} "
                       f"[{r.value_range[0]}–{r.value_range[1]}]")
        else:
            samples = str(r.value_samples)
        lines.append(
            f"| {r.name} | {r.value} | {r.unit} | {base} | "
            f"{r.speedup if r.speedup is not None else '—'} | {samples} |"
        )
    return "\n".join(lines)


def bench_pipeline(quick: bool) -> List[Row]:
    """--suite pipeline: the 1F1B pipeline ablation behind PIPELINE_GATE.

    One small conv model, FIXED global batch, M=4 microbatches; stages
    1/2/4 partition the 8 virtual devices into (stage, data) meshes of
    (1,8)/(2,4)/(4,2) and run train/pipeline_schedule.py's 1F1B step
    against the flat 8-device data-ring step on identical data:

    - pipe_img_s_S{S} rows time the step (baseline_src carries the
      3-step loss delta vs the flat ring — the in-row parity audit);
    - pipe_bubble_S{S} rows report the schedule's OWN idle fraction,
      counted from the (T, S) validity tables, against the closed form
      (S-1)/(S-1+M) — equal by construction of a correct 1F1B table,
      so any drift means the schedule lost work slots.

    The gate (the playbook's contract line): stages=1 bit-exact vs the
    flat ring, stages 2/4 within 1e-5, every counted bubble equal to the
    closed form.  On CPU the wall-clock rows are context, not the gate —
    8 virtual devices share the host's cores, so pipeline wall-clock
    "speedup" is meaningless here; the gate is about correctness of the
    schedule, the thing that IS portable to the TPU mesh."""
    from parallel_cnn_tpu.config import CommConfig, MeshConfig, PipelineConfig
    from parallel_cnn_tpu.nn import layers as L
    from parallel_cnn_tpu.nn.core import Sequential
    from parallel_cnn_tpu.parallel import mesh as mesh_lib
    from parallel_cnn_tpu.parallel import pipeline as pipe_lib
    from parallel_cnn_tpu.train import zoo
    from parallel_cnn_tpu.train.pipeline_schedule import make_pipeline_step

    n_dev = len(jax.devices())
    if n_dev < 8:
        raise RuntimeError(
            f"--suite pipeline needs >=8 devices for the stages 1/2/4 "
            f"sweep (got {n_dev}); run under "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )

    model_fn = lambda: Sequential([  # noqa: E731 — fresh params per leg
        L.Conv2D(4, (3, 3)), L.ReLU(), L.MaxPool(),
        L.Flatten(), L.Dense(10),
    ])
    in_shape = (8, 8, 3)
    accum = 4
    global_batch = 64
    n_steps = 3
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n_steps, global_batch, *in_shape)).astype(np.float32)
    Y = rng.integers(0, 10, size=(n_steps, global_batch)).astype(np.int32)
    comm = CommConfig(impl="ring")

    def run_losses(step, mesh, model):
        opt = zoo.make_optimizer(0.1, momentum=0.9)
        st = mesh_lib.replicate(
            mesh, zoo.init_state(model, jax.random.key(7), in_shape, opt)
        )
        losses = []
        for i in range(n_steps):
            st, loss = step(st, jnp.asarray(X[i]), jnp.asarray(Y[i]))
            losses.append(float(loss))
        return losses, st

    # Flat 8-device data-ring reference (the thing the pipeline must
    # match numerically while spending fewer devices on the data axis).
    ref_model = model_fn()
    ref_mesh = mesh_lib.make_mesh(MeshConfig(data=n_dev, model=1))
    ref_opt = zoo.make_optimizer(0.1, momentum=0.9)
    ref_step = zoo.make_train_step(
        ref_model, ref_opt, accum_steps=accum, mesh=ref_mesh, comm=comm
    )
    ref_losses, _ = run_losses(ref_step, ref_mesh, ref_model)

    rows: List[Row] = []
    gate_ok = True
    for n_stage in (1, 2, 4):
        model = model_fn()
        pmesh = mesh_lib.make_pipeline_mesh(n_stage)
        pcfg = PipelineConfig(stages=n_stage)
        opt = zoo.make_optimizer(0.1, momentum=0.9)
        step = make_pipeline_step(
            model, opt, accum_steps=accum, mesh=pmesh,
            pipeline=pcfg, in_shape=in_shape, comm=comm,
        )
        losses, _ = run_losses(step, pmesh, model)
        delta = max(abs(a - b) for a, b in zip(losses, ref_losses))
        tol = 0.0 if n_stage == 1 else 1e-5
        if delta > tol:
            gate_ok = False

        def thunk(carry, step=step, mesh=pmesh, model=model):
            if carry is None:
                o = zoo.make_optimizer(0.1, momentum=0.9)
                st = mesh_lib.replicate(
                    mesh, zoo.init_state(model, jax.random.key(7),
                                         in_shape, o)
                )
            else:
                st = carry[0]
            return step(st, jnp.asarray(X[0]), jnp.asarray(Y[0]))

        sec = _sync_time(thunk, repeats=3 if quick else 10)
        rows.append(Row(
            f"pipe_img_s_S{n_stage}", round(global_batch / sec, 1),
            "img/sec", None,
            f"max loss delta vs flat ring {delta:.2e} (tol {tol:g})",
        ).finish())

        # Schedule-counted bubble vs the closed form — exact by
        # construction; counted from the validity tables the step itself
        # dispatches on, so the row audits the real schedule.
        fv, bv = None, None
        _, fv, _, bv = pipe_lib.schedule_arrays(n_stage, accum)
        ticks = pipe_lib.n_ticks(n_stage, accum)
        counted = 1.0 - (int(fv.sum()) + int(bv.sum())) / (ticks * n_stage)
        closed = pipe_lib.bubble_fraction(n_stage, accum)
        if abs(counted - closed) > 1e-12:
            gate_ok = False
        rows.append(Row(
            f"pipe_bubble_S{n_stage}", round(counted, 4), "idle fraction",
            None, f"closed form (S-1)/(S-1+M) = {closed:.4f}",
        ).finish())

    print(
        f"PIPELINE_GATE {'PASS' if gate_ok else 'FAIL'}: stages 1/2/4 "
        f"parity vs flat ring (bit-exact / <=1e-5) and schedule bubble "
        f"== (S-1)/(S-1+M) at M={accum}",
        flush=True,
    )
    if not gate_ok:
        raise RuntimeError("PIPELINE_GATE FAIL — see pipe_* rows")
    return rows


def bench_autotune(quick: bool) -> List[Row]:
    """--suite autotune: the cost-model autotuner behind AUTOTUNE_GATE.

    Leg 1 — ranking validation: four candidate plans that differ ONLY
    in the dimensions an 8-virtual-device CPU host can actually measure
    (accumulation factor → scan/collective pass count, pipeline stages →
    1F1B bubble) are scored by the analytic model under the ``cpu-emu``
    hardware profile and then timed for real on identical data.  The
    gate is analysis.autotune.order_gate: the measured throughput
    ordering must agree with the model on >= 75% of the pairs the model
    separates by >= 1.10x (near-ties don't vote — CPU noise can't
    adjudicate them).  The comm-impl/wire-dtype dimensions are NOT
    measured here — virtual devices share one memory bus, so wire bytes
    don't cost wall-clock; those closed forms are validated exactly, by
    byte accounting, in the graftcheck cost family (docs/autotuning.md
    "Ranking validation" has the split).  Anti-vacuity: a doctored
    table that inverts the model's predictions must FAIL the same gate.

    Leg 2 — predictive autoscaler: a flash crowd against a 1→2-replica
    lenet_ref stack with admission ON (the EWMAs the capacity planner
    reads) and a slow-replica stall arming a real capacity deficit.
    The serve SLO is set far above CPU latency so the REACTIVE
    classifier never trips — any scale-up must come from the predictive
    branch (serve/capacity.py).  Gates: >= 1 scale-up whose journal
    event carries reason="predictive", ZERO sheds journaled before the
    first scale-up (journal seq order), zero unrecovered shed rate, and
    server-side conservation.  PR 11's reactive SERVE_SLO_GATE legs run
    unchanged in --suite serve.

    Any violated expectation appends an error row (rc 1) and flips the
    contract line to AUTOTUNE_GATE FAIL — playbook.sh's tune mode greps
    for it."""
    import tempfile

    from parallel_cnn_tpu import obs as obs_lib
    from parallel_cnn_tpu.analysis import autotune as at
    from parallel_cnn_tpu.analysis import hw_profiles
    from parallel_cnn_tpu.config import (CommConfig, MeshConfig, ObsConfig,
                                         PipelineConfig, ServeConfig)
    from parallel_cnn_tpu.nn import layers as L
    from parallel_cnn_tpu.nn.core import Sequential
    from parallel_cnn_tpu.parallel import mesh as mesh_lib
    from parallel_cnn_tpu.resilience.chaos import ChaosMonkey
    from parallel_cnn_tpu.serve import (AutoScaler, CapacityModel, get,
                                        scenarios, serve_stack)
    from parallel_cnn_tpu.train import zoo
    from parallel_cnn_tpu.train.pipeline_schedule import make_pipeline_step

    n_dev = len(jax.devices())
    if n_dev < 8:
        raise RuntimeError(
            f"--suite autotune needs >=8 devices (got {n_dev}); run under "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )

    rows: List[Row] = []
    failures: List[str] = []

    # -- leg 1: measured ranking vs the model (cpu-emu profile) ----------
    model_fn = lambda: Sequential([  # noqa: E731 — fresh params per leg
        L.Conv2D(4, (3, 3)), L.ReLU(), L.MaxPool(),
        L.Flatten(), L.Dense(10),
    ])
    in_shape = (8, 8, 3)
    global_batch = 64
    mp = at.profile_module(model_fn(), in_shape, name="bench_cnn")
    hw = hw_profiles.get_profile("cpu-emu")

    # CPU-measurable dimensions only; index 2 (k4-s2) doubles as the
    # hand-set "untuned default" row the chosen plan must beat.
    cands = (
        at.Plan(comm_impl="ring", wire_dtype="float32", overlap=True,
                accum=2),
        at.Plan(comm_impl="ring", wire_dtype="float32", overlap=True,
                accum=8),
        at.Plan(comm_impl="ring", wire_dtype="float32", overlap=False,
                accum=4, stages=2),
        at.Plan(comm_impl="ring", wire_dtype="float32", overlap=False,
                accum=4, stages=4),
    )
    default_idx = 2
    predicted = [
        at.score_plan(p, mp, hw, global_batch=global_batch,
                      n_dev=n_dev).img_s
        for p in cands
    ]

    rng = np.random.default_rng(0)
    X = rng.normal(size=(global_batch, *in_shape)).astype(np.float32)
    Y = rng.integers(0, 10, size=(global_batch,)).astype(np.int32)

    measured: List[float] = []
    for p in cands:
        model = model_fn()
        comm = CommConfig(impl="ring", wire_dtype="float32",
                          overlap=p.overlap)
        opt = zoo.make_optimizer(0.1, momentum=0.9)
        if p.stages > 1:
            mesh = mesh_lib.make_pipeline_mesh(p.stages)
            step = make_pipeline_step(
                model, opt, accum_steps=p.accum, mesh=mesh,
                pipeline=PipelineConfig(stages=p.stages),
                in_shape=in_shape, comm=comm,
            )
        else:
            mesh = mesh_lib.make_mesh(MeshConfig(data=n_dev, model=1))
            step = zoo.make_train_step(
                model, opt, accum_steps=p.accum, mesh=mesh, comm=comm
            )

        def thunk(carry, step=step, mesh=mesh, model=model):
            if carry is None:
                o = zoo.make_optimizer(0.1, momentum=0.9)
                st = mesh_lib.replicate(
                    mesh, zoo.init_state(model, jax.random.key(7),
                                         in_shape, o)
                )
            else:
                st = carry[0]
            return step(st, jnp.asarray(X), jnp.asarray(Y))

        sec = _sync_time(thunk, repeats=3 if quick else 10)
        measured.append(global_batch / sec)

    for p, pred, meas in zip(cands, predicted, measured):
        rows.append(Row(
            f"autotune_img_s_{p.label()}", round(meas, 1), "img/sec",
            None, f"model predicts {pred:.0f} img/s (cpu-emu)",
        ).finish())

    gate_ok, summary = at.order_gate(predicted, measured)
    if not gate_ok:
        failures.append(f"ranking: {summary}")
    # Anti-vacuity: inverting every prediction (1/x keeps the separation
    # ratios, flips the order) must fail the same gate.
    doctored_ok, _ = at.order_gate([1.0 / v for v in predicted], measured)
    if doctored_ok:
        failures.append(
            "ranking: the doctored (inverted) table PASSED the order "
            "gate — the gate is vacuous"
        )
    best_idx = max(range(len(cands)), key=lambda i: predicted[i])
    if measured[best_idx] < measured[default_idx]:
        failures.append(
            f"chosen plan {cands[best_idx].label()} measured "
            f"{measured[best_idx]:.0f} img/s, below the untuned default "
            f"{cands[default_idx].label()} at {measured[default_idx]:.0f}"
        )
    rows.append(Row(
        "autotune_rank_agreement", 1.0 if gate_ok else 0.0, "gate",
        None, f"{summary}; doctored table "
              f"{'FAILED (good)' if not doctored_ok else 'passed (BAD)'}",
    ).finish())

    # -- leg 2: predictive scale-up before any shed ----------------------
    handle = get("lenet_ref")
    obs_dir = tempfile.mkdtemp(prefix="pcnn_autotune_obs_")
    obs = obs_lib.from_config(
        ObsConfig(trace=True, dir=obs_dir, jax_annotations=False),
        run="autotune_pred",
    )
    # SLO far above CPU latency: the reactive classifier can never trip,
    # so any scale-up is the predictive branch's.  Deep queue + generous
    # admission budget: nothing sheds while the planner reacts.
    cfg = ServeConfig(
        model="lenet_ref", max_batch=8, max_wait_ms=1.0,
        queue_depth=2048, admission=True, slo_ms=2000.0, window_s=1.0,
    )
    pool, batcher = serve_stack(
        handle, cfg, obs=obs,
        chaos=ChaosMonkey.from_spec("slow-replica@3:400"),
    )
    capacity = CapacityModel(batcher.admission, max_batch=cfg.max_batch,
                             headroom=0.5)
    scaler = AutoScaler(pool, batcher, min_replicas=1, max_replicas=2,
                        slo_ms=cfg.slo_ms, hysteresis=2, cooldown_s=1.0,
                        interval_s=0.05, capacity=capacity, obs=obs)
    try:
        with scaler:
            rep = scenarios.run("flash-crowd", batcher, seed=7,
                                p99_ms=2000.0)
        snap = scaler.snapshot()
    finally:
        batcher.close()
    arts = obs.finish()
    events = obs_lib.read_journal(arts["journal"])
    ups = [e for e in events if e["kind"] == "scale_up"]
    first_up_seq = ups[0]["seq"] if ups else None
    sheds_before = [
        e for e in events if e["kind"] == "shed"
        and (first_up_seq is None or e["seq"] < first_up_seq)
    ]
    rows.append(Row(
        "autotune_predictive_flash_crowd", round(rep.shed_rate, 4),
        "unrecovered shed rate",
        baseline_src=(
            f"scale_ups {snap['scale_ups']} "
            f"(predictive {snap['predictive_ups']}), "
            f"sheds before first scale-up {len(sheds_before)}, "
            f"routable {snap['routable']}"
        ),
    ).finish())
    if not rep.conservation_ok:
        failures.append(f"predictive: conservation {rep.server}")
    if not ups:
        failures.append(
            "predictive: no scale-up despite the armed straggler "
            "collapsing the planner's service rate"
        )
    elif ups[0].get("reason") != "predictive":
        failures.append(
            f"predictive: first scale-up reason "
            f"{ups[0].get('reason')!r}, not 'predictive' — the reactive "
            "loop beat the planner"
        )
    if sheds_before:
        failures.append(
            f"predictive: {len(sheds_before)} sheds journaled BEFORE "
            "the first scale-up (the planner was late)"
        )
    if rep.shed_rate != 0.0:
        failures.append(
            f"predictive: unrecovered shed rate {rep.shed_rate:.4f} "
            "after the flash crowd"
        )

    if failures:
        rows.append(Row(
            "error_autotune_gate", -1.0, "error",
            baseline_src="; ".join(failures),
        ))
    print(
        "AUTOTUNE_GATE "
        + ("PASS: measured ranking agrees with the cost model, doctored "
           "table trips the gate, predictive scale-up landed before any "
           "shed"
           if not failures else "FAIL: " + "; ".join(failures)),
        flush=True,
    )
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--json", default=None)
    ap.add_argument("--md", default=None)
    ap.add_argument(
        "--suite",
        default="all",
        choices=["all", "lenet", "phases", "dp", "zoo", "parity", "ops",
                 "comm", "northstar", "serve", "net", "fused", "cost",
                 "obs", "elastic", "pipeline", "autotune"],
    )
    args = ap.parse_args(argv)

    # Never hang on a dead TPU tunnel (bench.py's round-1 lesson, applied
    # to the suite harness too): probe default-backend health in a
    # subprocess and fall back to a labeled CPU run. No-op when
    # PCNN_JAX_PLATFORMS already pinned the platform.
    platform = _bench._resolve_platform()
    print(f"[platform] {platform}", flush=True)

    suites = {
        "lenet": bench_lenet_throughput,
        "parity": bench_lenet_parity_epoch,
        "phases": bench_phases,
        "ops": bench_ops_paths,
        "dp": bench_dp_scaling,
        "zoo": bench_zoo,
        "comm": bench_comm,
        "northstar": bench_northstar,
        "serve": bench_serve,
        "net": bench_net,
        "fused": bench_fused,
        "cost": bench_cost,
        "obs": bench_obs,
        "elastic": bench_elastic,
        "pipeline": bench_pipeline,
        "autotune": bench_autotune,
    }
    picked = suites.values() if args.suite == "all" else [suites[args.suite]]

    rows: List[Row] = []
    for fn in picked:
        # Labeled, not fatal (same convention as bench.py): one failing
        # suite must not abort the run with no rows/JSON/MD written.
        try:
            rows.extend(fn(args.quick))
            print(f"[{fn.__name__}] done", flush=True)
        except Exception as e:  # noqa: BLE001 — converted to a labeled row
            rows.append(Row(f"error_{fn.__name__}", -1.0, "error",
                            baseline=None,
                            # Error text rides the baseline-source column
                            # (render_md prints it where a baseline would
                            # go) — deliberate column reuse, not a typo.
                            baseline_src=f"{type(e).__name__}: {e}"))
            print(f"[{fn.__name__}] FAILED: {e}", flush=True)

    print(render_md(rows))
    if args.json:
        with open(args.json, "w") as f:
            json.dump([asdict(r) for r in rows], f, indent=2)
    if args.md:
        with open(args.md, "w") as f:
            f.write(
                f"# Benchmark results\n\nplatform: "
                f"{platform} ×{len(jax.devices())}\n\n"
                + render_md(rows)
                + "\n"
            )
    # Error rows are labeled in the output, but the process must still
    # exit nonzero so automation gating on exit status sees the failure.
    return 1 if any(r.unit == "error" for r in rows) else 0


if __name__ == "__main__":
    raise SystemExit(main())
