#!/bin/bash
# Chip-time playbook: the measurements to (re-)run whenever the TPU relay
# is healthy (docs/round4_summary.md; VERDICT r4 next-round #1).
#
#   bash benches/playbook.sh [full|headline] [tag]
#
#   full      sanity probe, Mosaic capability probes, bench.py headline,
#             zoo suite — the complete evidence set for a round (~1-2 h).
#   headline  bench.py headline line only (~10-20 min) — the cheap repeat
#             for every subsequent heal; lines append, and the driver
#             headline is a median over same-session samples.
#   comm-multihost
#             2-process hierarchical-collective smoke
#             (benches/comm_multihost.py): weak-scaling rows + the
#             hier-vs-psum parity gate. CPU-only and self-contained —
#             runnable without the relay, so it can gate commits too.
#   check     graftcheck with the cost/sharding families
#             (`python -m parallel_cnn_tpu check --cost`): static comm
#             bytes vs the closed-form tables, peak-HBM accounting, the
#             DCN/HBM ratchet. CPU-only, gates commits like
#             comm-multihost; the report grep is the contract line.
#   obs       observability overhead gate (benches/run.py --suite obs):
#             traced-vs-untraced step throughput pairs; tracing must hold
#             >= 0.95x untraced. CPU-only and self-contained — gates
#             commits like comm-multihost; OBS_GATE is the contract line.
#   elastic   elastic-runtime gate (benches/run.py --suite elastic):
#             resize downtime / reshard-cost rows on an 8-virtual-device
#             CPU mesh, gated on the 8->4->8 resize-lap loss parity
#             (<= 1e-5) and pure-reshard bit-exactness. CPU-only and
#             self-contained — gates commits like comm-multihost;
#             ELASTIC_GATE is the contract line.
#   async     straggler-tolerant async-DP gate (benches/run.py --suite
#             comm, final leg): sync ring vs bounded-staleness (S=2) vs
#             EASGD on the virtual-clock harness, clean and under chaos
#             slow-worker@2:400, gated both ways (async holds >= 0.8x
#             clean throughput while the sync ring is asserted to
#             degrade below it) with seeded 3-step loss deltas <= 1e-2
#             and the staleness ledger <= S. CPU-only and self-contained
#             — gates commits like comm-multihost; ASYNC_GATE is the
#             contract line.
#   pipeline  1F1B pipeline-parallel gate (benches/run.py --suite
#             pipeline): stages 1/2/4 over the (stage, data) mesh on 8
#             virtual CPU devices, gated on stages=1 bit-exactness and
#             stages 2/4 <= 1e-5 parity vs the flat data ring, plus the
#             schedule-counted bubble fraction equal to the closed form
#             (S-1)/(S-1+M). CPU-only and self-contained — gates commits
#             like comm-multihost; PIPELINE_GATE is the contract line.
#   net       network front-door gate (benches/run.py --suite net):
#             cold-vs-warm AOT disk-cache cold start (warm must compile
#             nothing), wire-vs-in-process throughput, and the net
#             scenario sweep over real loopback sockets (steady /
#             slow-loris reap / supervised kill-endpoint respawn /
#             unsupervised trip / hot-swap zero-failed). CPU-only and
#             self-contained — gates commits like comm-multihost;
#             SERVE_NET_GATE is the contract line.
#   tune      autotuner gate (benches/run.py --suite autotune): the cost
#             model's predicted plan ranking vs measured throughput on
#             the 8-virtual-device CPU mesh (pairwise order gate, with
#             the doctored-inversion anti-vacuity check) plus the
#             predictive-autoscaler flash-crowd leg (first scale-up
#             carries reason=predictive and lands before any shed).
#             CPU-only and self-contained — gates commits like
#             comm-multihost; AUTOTUNE_GATE is the contract line.
#   serve-chaos
#             SLO-guarded serving gate (benches/run.py --suite serve):
#             seeded scenario suites (diurnal / flash-crowd /
#             slow-client / chaos-kill clean, chaos-slow expected-trip)
#             plus autoscaler flash-crowd recovery, judged on explicit
#             p99 / shed-rate / conservation gates. CPU-only and
#             self-contained — gates commits like comm-multihost;
#             SERVE_SLO_GATE is the contract line.
#
# All artifacts append/write under docs/ with the given tag (default: the
# UTC date), so repeated runs accumulate evidence instead of overwriting.
# Run via benches/watch.py to have this fire automatically at relay heal.
set -u -o pipefail
MODE="${1:-full}"
TAG="${2:-${PCNN_ROUND_TAG:-$(date -u +%Y%m%d)}}"
OVERALL=0
cd "$(dirname "$0")/.."
# benches/*.py import parallel_cnn_tpu; invoked as scripts their sys.path[0]
# is benches/, so the repo root must be on PYTHONPATH explicitly.
export PYTHONPATH="${PYTHONPATH:+$PYTHONPATH:}$PWD"
LOG="docs/playbook_${TAG}.log"
echo "=== playbook ${MODE} start $(date -u +%FT%TZ) ===" >> "$LOG"

if [ "$MODE" = "comm-multihost" ]; then
  echo "--- comm-multihost smoke ---" >> "$LOG"
  OUT="docs/comm_multihost_${TAG}.txt"
  timeout 900 python benches/comm_multihost.py > "$OUT" 2>&1
  RC=$?; echo "comm-multihost rc=$RC" >> "$LOG"
  # The gate line is the contract: both legs' hier-vs-psum parity <= 1e-5.
  grep -q 'COMM_MULTIHOST_GATE PASS' "$OUT" || RC=1
  [ $RC -ne 0 ] && OVERALL=1
  echo "=== playbook ${MODE} end rc=${OVERALL} $(date -u +%FT%TZ) ===" >> "$LOG"
  exit $OVERALL
fi

if [ "$MODE" = "check" ]; then
  echo "--- graftcheck --cost gate ---" >> "$LOG"
  OUT="docs/check_cost_${TAG}.txt"
  # 8 virtual devices so the zoo/hier traces (and hence the byte tables)
  # match the documented 2-host emulated mesh exactly.
  timeout 900 env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m parallel_cnn_tpu check --cost > "$OUT" 2>&1
  RC=$?; echo "check --cost rc=$RC" >> "$LOG"
  # The gate line is the contract: zero gating errors on a clean tree.
  grep -q 'graftcheck: 0 gating error(s)' "$OUT" || RC=1
  [ $RC -ne 0 ] && OVERALL=1
  echo "=== playbook ${MODE} end rc=${OVERALL} $(date -u +%FT%TZ) ===" >> "$LOG"
  exit $OVERALL
fi

if [ "$MODE" = "obs" ]; then
  echo "--- obs overhead gate ---" >> "$LOG"
  OUT="docs/obs_${TAG}.txt"
  timeout 900 env JAX_PLATFORMS=cpu \
    python benches/run.py --quick --suite obs > "$OUT" 2>&1
  RC=$?; echo "obs rc=$RC" >> "$LOG"
  # The gate line is the contract: traced throughput >= 0.95x untraced.
  grep -q 'OBS_GATE PASS' "$OUT" || RC=1
  [ $RC -ne 0 ] && OVERALL=1
  echo "=== playbook ${MODE} end rc=${OVERALL} $(date -u +%FT%TZ) ===" >> "$LOG"
  exit $OVERALL
fi

if [ "$MODE" = "elastic" ]; then
  echo "--- elastic resize gate ---" >> "$LOG"
  OUT="docs/elastic_${TAG}.txt"
  # 8 virtual devices: the lap's worlds (8 and 4) need a full-size mesh.
  timeout 900 env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benches/run.py --quick --suite elastic > "$OUT" 2>&1
  RC=$?; echo "elastic rc=$RC" >> "$LOG"
  # The gate line is the contract: lap parity <= 1e-5 + bit-exact reshard.
  grep -q 'ELASTIC_GATE PASS' "$OUT" || RC=1
  [ $RC -ne 0 ] && OVERALL=1
  echo "=== playbook ${MODE} end rc=${OVERALL} $(date -u +%FT%TZ) ===" >> "$LOG"
  exit $OVERALL
fi

if [ "$MODE" = "async" ]; then
  echo "--- async straggler gate ---" >> "$LOG"
  OUT="docs/async_${TAG}.txt"
  # 8 virtual devices: the comm suite's ring/hier legs need the full
  # emulated mesh; the async leg itself is host-side (virtual clock).
  timeout 900 env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benches/run.py --quick --suite comm > "$OUT" 2>&1
  RC=$?; echo "async rc=$RC" >> "$LOG"
  # The gate line is the contract: both-ways straggler ratios + bounded
  # loss deltas + ledger <= S.
  grep -q 'ASYNC_GATE PASS' "$OUT" || RC=1
  [ $RC -ne 0 ] && OVERALL=1
  echo "=== playbook ${MODE} end rc=${OVERALL} $(date -u +%FT%TZ) ===" >> "$LOG"
  exit $OVERALL
fi

if [ "$MODE" = "pipeline" ]; then
  echo "--- pipeline 1F1B gate ---" >> "$LOG"
  OUT="docs/pipeline_${TAG}.txt"
  # 8 virtual devices: the stages 1/2/4 sweep needs (1,8)/(2,4)/(4,2)
  # (stage, data) meshes over a full-size device set.
  timeout 900 env JAX_PLATFORMS=cpu PCNN_JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benches/run.py --quick --suite pipeline > "$OUT" 2>&1
  RC=$?; echo "pipeline rc=$RC" >> "$LOG"
  # The gate line is the contract: parity (bit-exact / <= 1e-5) + the
  # schedule bubble equal to (S-1)/(S-1+M).
  grep -q 'PIPELINE_GATE PASS' "$OUT" || RC=1
  [ $RC -ne 0 ] && OVERALL=1
  echo "=== playbook ${MODE} end rc=${OVERALL} $(date -u +%FT%TZ) ===" >> "$LOG"
  exit $OVERALL
fi

if [ "$MODE" = "net" ]; then
  echo "--- serve network front-door gate ---" >> "$LOG"
  OUT="docs/serve_net_${TAG}.txt"
  # 8 virtual devices so the hot-swap leg's grown replica gets its own
  # device slot (same mesh the tests and the serve suite assume).
  timeout 900 env JAX_PLATFORMS=cpu PCNN_JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benches/run.py --quick --suite net > "$OUT" 2>&1
  RC=$?; echo "net rc=$RC" >> "$LOG"
  # The gate line is the contract: zero warm-start compiles, balanced
  # wire ledgers, the loris reaped, the supervised kill ridden through,
  # the unsupervised trip proven, the hot swap zero-failed.
  grep -q 'SERVE_NET_GATE PASS' "$OUT" || RC=1
  [ $RC -ne 0 ] && OVERALL=1
  echo "=== playbook ${MODE} end rc=${OVERALL} $(date -u +%FT%TZ) ===" >> "$LOG"
  exit $OVERALL
fi

if [ "$MODE" = "tune" ]; then
  echo "--- autotune ranking + predictive-scaler gate ---" >> "$LOG"
  OUT="docs/autotune_${TAG}.txt"
  # 8 virtual devices: the measured candidates span flat data rings and
  # (stage, data) pipeline meshes over the full emulated device set.
  timeout 900 env JAX_PLATFORMS=cpu PCNN_JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benches/run.py --quick --suite autotune > "$OUT" 2>&1
  RC=$?; echo "tune rc=$RC" >> "$LOG"
  # The gate line is the contract: measured ranking agrees with the
  # model, the doctored table trips, the predictive scale-up lands
  # before any shed.
  grep -q 'AUTOTUNE_GATE PASS' "$OUT" || RC=1
  [ $RC -ne 0 ] && OVERALL=1
  echo "=== playbook ${MODE} end rc=${OVERALL} $(date -u +%FT%TZ) ===" >> "$LOG"
  exit $OVERALL
fi

if [ "$MODE" = "serve-chaos" ]; then
  echo "--- serve SLO + chaos scenario gate ---" >> "$LOG"
  OUT="docs/serve_slo_${TAG}.txt"
  # 8 virtual devices so the 2-replica rows and the autoscaler's grown
  # replica each get their own device slot.
  timeout 900 env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benches/run.py --quick --suite serve > "$OUT" 2>&1
  RC=$?; echo "serve-chaos rc=$RC" >> "$LOG"
  # The gate line is the contract: clean scenarios pass their p99/shed
  # gates AND the armed slow-replica run trips its gate (anti-vacuity).
  grep -q 'SERVE_SLO_GATE PASS' "$OUT" || RC=1
  [ $RC -ne 0 ] && OVERALL=1
  echo "=== playbook ${MODE} end rc=${OVERALL} $(date -u +%FT%TZ) ===" >> "$LOG"
  exit $OVERALL
fi

if [ "$MODE" = "full" ]; then
  echo "--- step 0: sanity ---" >> "$LOG"
  timeout 300 python -c "import jax; print(jax.devices())" >> "$LOG" 2>&1
  RC=$?; echo "step 0 rc=$RC" >> "$LOG"; [ $RC -ne 0 ] && OVERALL=1

  echo "--- step 1: mosaic probes ---" >> "$LOG"
  timeout 900 python benches/mosaic_probe.py > "docs/mosaic_probe_${TAG}.txt" 2>&1
  RC=$?; echo "step 1 rc=$RC" >> "$LOG"; [ $RC -ne 0 ] && OVERALL=1
fi

echo "--- step 2: bench.py headline ---" >> "$LOG"
# Append the line only if bench.py SUCCEEDED *on the TPU* — a timeout or
# crash must not push a partial last-stdout-line into the artifact, and a
# labeled CPU-fallback line (bench.py exits 0 for those, by contract)
# must not pollute the TPU median-over-samples either: CPU pollution of
# this exact artifact is what the playbook/watcher tooling exists to
# prevent. A clean CPU line still counts as a FAILED playbook run so the
# watcher keeps retrying the full evidence set at the next heal.
HEADLINE_TMP="$(mktemp)"
timeout 2400 python bench.py 2>> "$LOG" | tail -1 > "$HEADLINE_TMP"
RC=$?; echo "step 2 rc=$RC" >> "$LOG"
if [ $RC -eq 0 ] && grep -q '"platform": "tpu"' "$HEADLINE_TMP"; then
  cat "$HEADLINE_TMP" >> "docs/bench_lines_${TAG}.jsonl"
else
  echo "step 2: no TPU headline line (rc=$RC, line: $(cat "$HEADLINE_TMP"))" >> "$LOG"
  OVERALL=1
fi
rm -f "$HEADLINE_TMP"

if [ "$MODE" = "full" ]; then
  echo "--- step 3: zoo suite ---" >> "$LOG"
  timeout 5400 python benches/run.py --suite zoo --json "docs/zoo_${TAG}.json" >> "$LOG" 2>&1
  RC=$?; echo "step 3 rc=$RC" >> "$LOG"; [ $RC -ne 0 ] && OVERALL=1
fi

echo "=== playbook ${MODE} end rc=${OVERALL} $(date -u +%FT%TZ) ===" >> "$LOG"
exit $OVERALL
