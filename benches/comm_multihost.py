"""Multi-host hierarchical-collective smoke bench (ISSUE 9).

Runs the SAME hierarchical two-level train step at two process counts —

  1 process x 4 virtual devices   (host axis emulated: 2x2 fold)
  2 processes x 4 virtual devices (host axis real: the inter-host shard
                                   exchange is a cross-process ppermute
                                   over gloo)

— each with an in-leg parity probe (hier vs psum on the same mesh,
3 optimizer steps from identical init) and a timed throughput section.
Per-device batch is FIXED (weak scaling): the 2-process leg does twice
the global work over twice the devices, so img/s-per-device directly
reads out what adding a host costs.

    python benches/comm_multihost.py          # parent: both legs + gate
    python benches/comm_multihost.py leg      # one measurement process

Parent prints parseable lines and exits 0 iff BOTH legs hold the ≤1e-5
hier-vs-psum parity contract:

    MULTIHOST_ROW procs=.. devices=.. ips=.. ips_per_dev=.. parity=..
    MULTIHOST_WEAK_SCALING eff=..   (per-dev 2proc / per-dev 1proc)
    COMM_MULTIHOST_GATE PASS|FAIL ...

On the CPU harness the "DCN" is localhost gloo — the efficiency number
is indicative; the parity gate is the hard contract either way.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PER_DEV_BATCH = 16
PROBE_STEPS = 3
TIMED_STEPS = 8
IN_SHAPE = (8, 8, 3)
PARITY_TOL = 1e-5


def run_leg() -> int:
    """One measurement process: joins the multi-process runtime when the
    PCNN_* env is set (2-proc leg), else runs single-process with an
    emulated 2-host fold of its 4 virtual devices — identical algorithm,
    only the host-axis transport differs."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")
    # Cross-process collectives on the CPU backend go through gloo; the
    # default ("none") hard-errors on the first multiprocess computation.
    # Single-process legs must NOT set it — without a distributed client
    # the gloo factory refuses to build the CPU backend at all.
    if os.environ.get("PCNN_COORDINATOR"):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # newer jax: gloo is the default, knob gone
            pass

    import numpy as np

    from parallel_cnn_tpu.parallel import distributed

    joined = distributed.initialize()

    import jax.numpy as jnp  # noqa: F401  (post-init import discipline)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from parallel_cnn_tpu.config import CommConfig
    from parallel_cnn_tpu.nn import core, layers
    from parallel_cnn_tpu.parallel import mesh as mesh_lib
    from parallel_cnn_tpu.train import zoo

    mesh = (mesh_lib.make_hier_mesh() if joined
            else mesh_lib.make_hier_mesh(n_hosts=2))
    n_total = mesh.devices.size
    global_batch = PER_DEV_BATCH * n_total

    model = core.Sequential([
        layers.Conv2D(4, (3, 3)), layers.BatchNorm(), layers.ReLU(),
        layers.MaxPool(), layers.Flatten(), layers.Dense(10),
    ])
    opt = zoo.make_optimizer(lr=0.05)

    rng = np.random.default_rng(456)
    x_host = rng.normal(size=(global_batch,) + IN_SHAPE).astype(np.float32)
    y_host = rng.integers(0, 10, (global_batch,)).astype(np.int32)

    def globalize(a, sharding):
        # make_array_from_callback: each process materializes only its
        # addressable shards — works identically at 1 and 2 processes.
        host = np.asarray(a)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx]
        )

    rep = NamedSharding(mesh, P())
    dat = mesh_lib.batch_sharding(mesh)
    x = globalize(x_host, dat)
    y = globalize(y_host, dat)

    def init_state():
        st = zoo.init_state(model, jax.random.key(7), IN_SHAPE, opt)
        return jax.tree_util.tree_map(lambda a: globalize(a, rep), st)

    losses = {}
    steps = {}
    for name, comm in (
        ("psum", CommConfig(impl="psum")),
        ("hier", CommConfig(impl="hierarchical", bucket_bytes=2048)),
    ):
        step = zoo.make_train_step(
            model, opt, accum_steps=2, mesh=mesh, comm=comm
        )
        st, loss = init_state(), None
        for _ in range(PROBE_STEPS):
            st, loss = step(st, x, y)
        jax.block_until_ready(loss)
        losses[name] = float(loss)
        steps[name] = step
    parity = abs(losses["hier"] - losses["psum"])

    # Timed section: the hier step is already compiled (probe above);
    # chain states so the donated buffers stay live.
    st = init_state()
    st, loss = steps["hier"](st, x, y)  # warm donation path
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(TIMED_STEPS):
        st, loss = steps["hier"](st, x, y)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    ips = TIMED_STEPS * global_batch / dt

    if jax.process_index() == 0:
        print(
            f"LEG procs={jax.process_count()} devices={n_total} "
            f"ips={ips:.2f} ips_per_dev={ips / n_total:.2f} "
            f"parity={parity:.3e}",
            flush=True,
        )
    return 0


def _leg_env(extra=None):
    env = dict(os.environ)
    for var in ("PCNN_COORDINATOR", "PCNN_NUM_PROCESSES", "PCNN_PROCESS_ID"):
        env.pop(var, None)
    # 4 virtual devices per process; run_leg pins the platform itself.
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    if extra:
        env.update(extra)
    return env


def _parse_leg(stdout: str) -> dict:
    for line in stdout.splitlines():
        if line.startswith("LEG "):
            return {
                k: v for k, v in
                (tok.split("=", 1) for tok in line.split()[1:])
            }
    raise RuntimeError(f"no LEG line in output:\n{stdout}")


def main() -> int:
    me = os.path.abspath(__file__)

    # Leg 1: single process, emulated 2-host mesh. A fresh interpreter so
    # the platform/device-count env is snapshotted cleanly.
    r1 = subprocess.run(
        [sys.executable, me, "leg"], env=_leg_env(), capture_output=True,
        text=True, timeout=600,
    )
    if r1.returncode != 0:
        print(r1.stdout, r1.stderr, sep="\n")
        print("COMM_MULTIHOST_GATE FAIL 1-proc leg crashed "
              f"(rc {r1.returncode})")
        return 1
    leg1 = _parse_leg(r1.stdout)

    # Leg 2: two real processes over a localhost coordinator.
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for rank in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, me, "leg"],
            env=_leg_env({
                "PCNN_COORDINATOR": f"127.0.0.1:{port}",
                "PCNN_NUM_PROCESSES": "2",
                "PCNN_PROCESS_ID": str(rank),
            }),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(rc != 0 for rc, _, _ in outs):
        for rc, out, err in outs:
            print(f"--- rank rc={rc} ---\n{out}\n{err}")
        print("COMM_MULTIHOST_GATE FAIL 2-proc leg crashed")
        return 1
    leg2 = _parse_leg(outs[0][1])

    p1, p2 = float(leg1["parity"]), float(leg2["parity"])
    d1, d2 = float(leg1["ips_per_dev"]), float(leg2["ips_per_dev"])
    for leg in (leg1, leg2):
        print(
            f"MULTIHOST_ROW procs={leg['procs']} devices={leg['devices']} "
            f"ips={leg['ips']} ips_per_dev={leg['ips_per_dev']} "
            f"parity={leg['parity']}"
        )
    eff = d2 / d1 if d1 > 0 else 0.0
    print(f"MULTIHOST_WEAK_SCALING eff={eff:.3f}")
    ok = p1 <= PARITY_TOL and p2 <= PARITY_TOL
    print(
        f"COMM_MULTIHOST_GATE {'PASS' if ok else 'FAIL'} "
        f"parity_1proc={p1:.3e} parity_2proc={p2:.3e} tol={PARITY_TOL:.0e}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "leg":
        sys.exit(run_leg())
    sys.exit(main())
