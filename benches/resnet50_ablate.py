"""ResNet-50 @224² single-chip MFU ablation (VERDICT r4 next #4).

Attribution by ablation, not trace-parsing (the container's profile-
plugin converter is version-broken): vary one axis at a time around the
config-#5 operating point (batch 64, grad accumulation 4 → microbatch
16, bf16 inputs) and read where the step time goes.

    PYTHONPATH=. python benches/resnet50_ablate.py [--steps 6]

Rows:
  accum sweep  — b64 at accum {4, 2, 1}: unrolled-accumulation overhead
                 + microbatch-size MXU effect in one axis.
  dtype        — b64 accum4 with f32 inputs: the BN/elementwise dtype
                 traffic lever (nn/layers.py normalizes at x.dtype).
  batch 32     — accum {2, 1} at constant microbatch 16 vs 32.

Each row is warmed (one step + full-pytree drain) then timed over
--steps steps with the single full-drain barrier discipline
(benches/run.py._drain hazard notes). OOM rows are labeled, not fatal.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path[:0] = [_REPO, os.path.join(_REPO, "benches")]

from run import _drain  # noqa: E402 — the documented full-pytree barrier

# fwd ≈ 4.1 GMACs = 8.2 GFLOP @224²; train ≈ 3× fwd. (The first committed
# run of this script used 4.1e9 — MACs, not FLOPs — so its MFU column
# reads exactly 2× low; throughputs unaffected.)
RESNET50_TRAIN_FLOPS_PER_IMAGE = 3 * 8.2e9
PEAK_BF16 = 197e12


def measure(batch, accum, dtype, steps):
    from parallel_cnn_tpu.nn import resnet
    from parallel_cnn_tpu.train import zoo

    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.uniform(0, 1, (batch, 224, 224, 3)).astype(np.float32)
    ).astype(dtype)
    y = jnp.asarray(rng.integers(0, 100, (batch,)).astype(np.int32))
    model = resnet.resnet50(100, cifar_stem=False)
    opt = zoo.make_optimizer(0.05)
    st = zoo.init_state(model, jax.random.key(0), (224, 224, 3), opt)
    step = zoo.make_train_step(model, opt, accum_steps=accum)
    st, _ = step(st, x, y)
    _drain(st)
    t0 = time.perf_counter()
    for _ in range(steps):
        st, _ = step(st, x, y)
    _drain(st)
    sec = (time.perf_counter() - t0) / steps
    ips = batch / sec
    mfu = RESNET50_TRAIN_FLOPS_PER_IMAGE * ips / PEAK_BF16
    return ips, mfu, sec


# Round-5 finding encoded as a second grid (invoked with --big): the first
# ablation measured ~flat ms/step across batch at fixed microbatch — the
# step is dispatch-bound at b<=64 through the relay — so MFU scales with
# GLOBAL batch at constant microbatch. Probe the big-batch regime.
def main_big(steps):
    grid = [
        ("b128_accum8_bf16 (microbatch 16)", 128, 8, jnp.bfloat16),
        ("b128_accum4_bf16 (microbatch 32)", 128, 4, jnp.bfloat16),
        ("b256_accum8_bf16 (microbatch 32)", 256, 8, jnp.bfloat16),
        ("b256_accum16_bf16 (microbatch 16)", 256, 16, jnp.bfloat16),
        ("b512_accum16_bf16 (microbatch 32)", 512, 16, jnp.bfloat16),
        ("b512_accum8_bf16 (microbatch 64)", 512, 8, jnp.bfloat16),
        ("b512_accum4_bf16 (microbatch 128)", 512, 4, jnp.bfloat16),
    ]
    print("| row | img/s | MFU | ms/step |")
    print("|---|---|---|---|")
    for name, b, a, dt in grid:
        try:
            ips, mfu, sec = measure(b, a, dt, steps)
            print(f"| {name} | {ips:.1f} | {mfu * 100:.1f}% | "
                  f"{sec * 1e3:.1f} |", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"| {name} | error | {type(e).__name__}: {e} | |"[:300],
                  flush=True)
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--big", action="store_true",
                    help="big-global-batch grid (dispatch-bound finding)")
    args = ap.parse_args()
    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(_REPO, ".jax_cache")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    if args.big:
        return main_big(args.steps)
    grid = [
        ("b64_accum4_bf16 (config #5 operating point)", 64, 4, jnp.bfloat16),
        ("b64_accum2_bf16 (microbatch 32)", 64, 2, jnp.bfloat16),
        ("b64_accum1_bf16 (no accumulation)", 64, 1, jnp.bfloat16),
        ("b64_accum4_f32 (dtype lever)", 64, 4, jnp.float32),
        ("b32_accum2_bf16 (microbatch 16, half batch)", 32, 2, jnp.bfloat16),
        ("b32_accum1_bf16 (microbatch 32, half batch)", 32, 1, jnp.bfloat16),
    ]
    print("| row | img/s | MFU | ms/step |")
    print("|---|---|---|---|")
    for name, b, a, dt in grid:
        try:
            ips, mfu, sec = measure(b, a, dt, args.steps)
            print(f"| {name} | {ips:.1f} | {mfu * 100:.1f}% | "
                  f"{sec * 1e3:.1f} |", flush=True)
        except Exception as e:  # noqa: BLE001 — labeled, not fatal
            print(f"| {name} | error | {type(e).__name__}: {e} | |"[:300],
                  flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
