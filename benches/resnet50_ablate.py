"""ResNet-50 @224² single-chip MFU ablation (VERDICT r4 next #4).

Attribution by ablation, not trace-parsing (the container's profile-
plugin converter is version-broken): vary one axis at a time around the
config-#5 operating point (batch 64, grad accumulation 4 → microbatch
16, bf16 inputs) and read where the step time goes.

    PYTHONPATH=. python benches/resnet50_ablate.py [--steps 6]

Rows:
  accum sweep  — b64 at accum {4, 2, 1}: unrolled-accumulation overhead
                 + microbatch-size MXU effect in one axis.
  dtype        — b64 accum4 with f32 inputs: the BN/elementwise dtype
                 traffic lever (nn/layers.py normalizes at x.dtype).
  batch 32     — accum {2, 1} at constant microbatch 16 vs 32.

Each row is warmed (one step + full-pytree drain) then timed over
--steps steps with the single full-drain barrier discipline
(benches/run.py._drain hazard notes). OOM rows are labeled, not fatal.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

RESNET50_TRAIN_FLOPS_PER_IMAGE = 3 * 4.1e9  # fwd ≈4.1 GFLOP @224², train ≈3×
PEAK_BF16 = 197e12


def _drain(tree) -> None:
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if hasattr(l, "block_until_ready")]
    acc = None
    for l in leaves:
        s = jnp.sum(jnp.abs(l.astype(jnp.float32)))
        acc = s if acc is None else acc + s
    float(acc)


def measure(batch, accum, dtype, steps):
    from parallel_cnn_tpu.nn import resnet
    from parallel_cnn_tpu.train import zoo

    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.uniform(0, 1, (batch, 224, 224, 3)).astype(np.float32)
    ).astype(dtype)
    y = jnp.asarray(rng.integers(0, 100, (batch,)).astype(np.int32))
    model = resnet.resnet50(100, cifar_stem=False)
    opt = zoo.make_optimizer(0.05)
    st = zoo.init_state(model, jax.random.key(0), (224, 224, 3), opt)
    step = zoo.make_train_step(model, opt, accum_steps=accum)
    st, _ = step(st, x, y)
    _drain(st)
    t0 = time.perf_counter()
    for _ in range(steps):
        st, _ = step(st, x, y)
    _drain(st)
    sec = (time.perf_counter() - t0) / steps
    ips = batch / sec
    mfu = RESNET50_TRAIN_FLOPS_PER_IMAGE * ips / PEAK_BF16
    return ips, mfu, sec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=6)
    args = ap.parse_args()
    jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    grid = [
        ("b64_accum4_bf16 (config #5 operating point)", 64, 4, jnp.bfloat16),
        ("b64_accum2_bf16 (microbatch 32)", 64, 2, jnp.bfloat16),
        ("b64_accum1_bf16 (no accumulation)", 64, 1, jnp.bfloat16),
        ("b64_accum4_f32 (dtype lever)", 64, 4, jnp.float32),
        ("b32_accum2_bf16 (microbatch 16, half batch)", 32, 2, jnp.bfloat16),
        ("b32_accum1_bf16 (microbatch 32, half batch)", 32, 1, jnp.bfloat16),
    ]
    print(f"| row | img/s | MFU | ms/step |")
    print(f"|---|---|---|---|")
    for name, b, a, dt in grid:
        try:
            ips, mfu, sec = measure(b, a, dt, args.steps)
            print(f"| {name} | {ips:.1f} | {mfu * 100:.1f}% | "
                  f"{sec * 1e3:.1f} |", flush=True)
        except Exception as e:  # noqa: BLE001 — labeled, not fatal
            print(f"| {name} | error | {type(e).__name__}: {e} | |"[:300],
                  flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
