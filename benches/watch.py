"""TPU relay watcher: fixed-interval probe, run the chip-time playbook on heal.

The axon relay that fronts the TPU goes down for hours at a time (it ate
the on-chip benchmark artifact in rounds 2-4). This watcher turns "hope a
human notices the heal" into a process:

    nohup python benches/watch.py --tag r5 >> docs/watch_r5.log 2>&1 &

Loop: probe backend health in a subprocess (hard timeout, so a hung relay
can never hang the watcher — same contract as bench.py's
``_resolve_platform``); while the chip is down, re-probe every
``--interval`` seconds (probes are cheap; outages last hours, so a fixed
short interval loses at most minutes of healed-chip time). At the first
heal run the FULL playbook (``benches/playbook.sh full``); once a full
run completes cleanly, later heals re-run only the cheap headline step
after ``--cooldown`` — lines append, and the driver headline is a median
over same-session samples, so every extra run strengthens the artifact.
A playbook run that fails (relay died mid-run, or only a CPU-fallback
line was produced) is retried at ``--interval``, not ``--cooldown``:
healed-chip windows are the scarce resource.

Probe/run/sleep are injectable for tests (tests/test_watch.py mocks all
three; no TPU or subprocess needed to verify the loop logic). The probe
itself is the SHARED implementation in parallel_cnn_tpu/utils/probe.py —
bench.py's wait loop uses the same one, so the two tools can't drift on
what "healthy" means, and the probe subprocess appends (never assigns)
the repo root onto PYTHONPATH. The default --interval equals the shared
RETRY_BACKOFF_CAP, aligning the watcher's poll with bench.py's
backed-off retry schedule.

Reference anchor: the reference committed measured numbers for every
backend it shipped (README.md:17-18, PDF Tables 1-8); this is the tooling
that keeps us able to do the same under an unreliable relay.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from parallel_cnn_tpu.utils.probe import (  # noqa: E402
    RETRY_BACKOFF_CAP,
    probe_once,
)


def watch(
    *,
    interval: float,
    cooldown: float,
    tag: str,
    playbook: str,
    max_runs: int = 0,
    probe=probe_once,
    run=subprocess.run,
    sleep=time.sleep,
) -> int:
    """Poll until healthy, run the playbook, repeat. Returns #runs done."""

    def _log(msg: str) -> None:
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        print(f"[watch {stamp}] {msg}", flush=True)

    runs = 0
    probes = 0
    full_done = False
    while max_runs <= 0 or runs < max_runs:
        probes += 1
        if probe():
            # Retry the FULL evidence set until one run completes cleanly
            # (a relay that dies mid-run, or a CPU-fallback headline,
            # exits the playbook nonzero); only then drop to the cheap
            # headline repeats.
            mode = "headline" if full_done else "full"
            _log(f"chip healthy (probe {probes}); running playbook mode={mode}")
            proc = run(["bash", playbook, mode, tag])
            rc = getattr(proc, "returncode", 0)
            if mode == "full" and rc == 0:
                full_done = True
            runs += 1
            if max_runs > 0 and runs >= max_runs:
                _log(f"playbook run {runs} finished rc={rc}; max runs reached")
                break
            # A failed run re-probes at the short interval — the chip
            # probably just died, and the next heal must not wait out a
            # full cooldown.
            delay = cooldown if rc == 0 else interval
            _log(f"playbook run {runs} finished rc={rc}; next probe in {delay:.0f}s")
            sleep(delay)
        else:
            _log(f"chip down (probe {probes}); retry in {interval:.0f}s")
            sleep(interval)
    return runs


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--tag", default=os.environ.get("PCNN_ROUND_TAG", ""),
                        help="artifact tag (docs/bench_lines_<tag>.jsonl etc.)")
    parser.add_argument("--interval", type=float,
                        default=RETRY_BACKOFF_CAP,
                        help="seconds between probes while the chip is "
                             "down (default: the shared probe retry cap)")
    parser.add_argument("--cooldown", type=float, default=3600.0,
                        help="seconds to wait after a successful playbook run")
    parser.add_argument("--max-runs", type=int, default=0,
                        help="stop after this many playbook runs (0 = forever)")
    parser.add_argument("--playbook",
                        default=os.path.join(os.path.dirname(__file__), "playbook.sh"))
    args = parser.parse_args(argv)
    tag = args.tag or time.strftime("%Y%m%d", time.gmtime())
    watch(interval=args.interval, cooldown=args.cooldown, tag=tag,
          playbook=args.playbook, max_runs=args.max_runs)
    return 0


if __name__ == "__main__":
    sys.exit(main())
