"""Mosaic capability + layout probes for the megakernel roof attack
(VERDICT r3 next #4 / docs/future_work.md §4). TPU-only: each probe
compiles a tiny Pallas kernel and reports LOWERED / REJECTED plus a
rough timing, so the round's on-chip time is spent measuring, not
authoring.

    python benches/mosaic_probe.py

Probes:
1. rank3-dot     — dot_general with a batch dim inside a TPU kernel
                   (the round-3 blocker for MXU-ing the conv taps).
2. lane-merge    — in-kernel reshape (25, Bb, 576) → (25, Bb*576)
                   (the other blocker: would let one (6,25)@(25,L) MXU
                   dot replace 150 VPU tap-FMA rows).
3. mxu-conv-L    — the (25, L=Bb*576) HOST-layout variant: one
                   (6,25)@(25,L) dot per block vs the 150-FMA loop,
                   timed head-to-head (feasibility of splitting the
                   fused kernel's conv onto the MXU with NO in-kernel
                   relayout — the (6,L) result then needs a
                   lane-split reshape to (Bb,576) per filter, probe 4).
4. lane-split    — in-kernel reshape (1, L) → (Bb, 576).

Each probe is wrapped: a Mosaic lowering rejection prints the error
class, never a crash. Exit code 0 always (informational tool).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BB = 128
L = BB * 576


def _run(name, fn):
    try:
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        first = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(10):
            out = fn()
        jax.block_until_ready(out)
        steady = (time.perf_counter() - t0) / 10
        print(f"[{name}] LOWERED  first={first * 1e3:.1f}ms "
              f"steady={steady * 1e6:.0f}us")
        return True
    except Exception as e:  # noqa: BLE001 — report, don't crash
        msg = f"{type(e).__name__}: {e}"
        print(f"[{name}] REJECTED {msg[:300]}")
        return False


def probe_rank3_dot():
    def kernel(a_ref, b_ref, o_ref):
        # (4, 64, 128) @ (4, 128, 64) batched over dim 0
        o_ref[:] = lax.dot_general(
            a_ref[:], b_ref[:],
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )

    a = jnp.ones((4, 64, 128), jnp.float32)
    b = jnp.ones((4, 128, 64), jnp.float32)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((4, 64, 64), jnp.float32),
    )(a, b)


def probe_lane_merge():
    def kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:].reshape(25, BB * 576)

    x = jnp.ones((25, BB, 576), jnp.float32)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((25, BB * 576), jnp.float32),
    )(x)


def probe_lane_split():
    def kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:].reshape(BB, 576)

    x = jnp.ones((1, L), jnp.float32)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((BB, 576), jnp.float32),
    )(x)


def _mxu_conv_L_kernel(w_ref, x_ref, o_ref):
    o_ref[:] = lax.dot_general(
        w_ref[:], x_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _vpu_conv_kernel(w_ref, x_ref, o_ref):
    # the megakernel's current form: 6 filters × 25 tap-FMAs on the VPU
    for m in range(6):
        acc = jnp.zeros((BB, 576), jnp.float32)
        for t in range(25):
            acc += w_ref[m, t] * x_ref[t]
        o_ref[m] = acc


def _mxu_conv_3d_kernel(w_ref, x_ref, o_ref):
    # (6,25) @ (25,BB,576) → (6,BB,576): rank-2 × rank-3 contraction, NO
    # batch dims and NO reshape — if Mosaic lowers this, the megakernel's
    # 150-FMA VPU conv loop swaps for one MXU dot with the SAME x layout
    # it already stages (taps-major) and the SAME output layout the pool
    # stage consumes. The r5 probes showed mxu-conv-L 7× faster than the
    # VPU loop but lane-split REJECTED; this shape needs neither reshape.
    o_ref[:] = lax.dot_general(
        w_ref[:], x_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def probe_mxu_conv_3d():
    w = jnp.ones((6, 25), jnp.float32)
    x = jnp.ones((25, BB, 576), jnp.bfloat16)
    return pl.pallas_call(
        _mxu_conv_3d_kernel,
        out_shape=jax.ShapeDtypeStruct((6, BB, 576), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024
        ),
    )(w, x)


def probe_mxu_conv_L():
    w = jnp.ones((6, 25), jnp.float32)
    x = jnp.ones((25, L), jnp.bfloat16)
    return pl.pallas_call(
        _mxu_conv_L_kernel,
        out_shape=jax.ShapeDtypeStruct((6, L), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024
        ),
    )(w, x)


def probe_vpu_conv_baseline():
    w = jnp.ones((6, 25), jnp.float32)
    x = jnp.ones((25, BB, 576), jnp.bfloat16)
    return pl.pallas_call(
        _vpu_conv_kernel,
        out_shape=jax.ShapeDtypeStruct((6, BB, 576), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024
        ),
    )(w, x)


ROWS = 1024


def _pair_dot_kernel(x_ref, w_ref, o_ref):
    # K=64, N=128 dot then lane-halves add: the N-packing candidate for
    # the zoo conv library's 64-channel stages (two taps' weights stacked
    # along N, halves summed after row-shift). Probes whether Mosaic
    # allows value slicing at a 64-lane offset (sub-lane-tile).
    out = lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = out[:, :64] + out[:, 64:]


def probe_pair_dot_laneslice():
    x = jnp.ones((ROWS, 64), jnp.bfloat16)
    w = jnp.ones((64, 128), jnp.bfloat16)
    return pl.pallas_call(
        _pair_dot_kernel,
        out_shape=jax.ShapeDtypeStruct((ROWS, 64), jnp.float32),
    )(x, w)


def _two_dot_kernel(x_ref, w_ref, o_ref):
    # the current formulation's shape: two separate K=N=64 dots
    a = lax.dot_general(
        x_ref[:], w_ref[:, :64], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    b = lax.dot_general(
        x_ref[:], w_ref[:, 64:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = a + b


def probe_two_dot_baseline():
    x = jnp.ones((ROWS, 64), jnp.bfloat16)
    w = jnp.ones((64, 128), jnp.bfloat16)
    return pl.pallas_call(
        _two_dot_kernel,
        out_shape=jax.ShapeDtypeStruct((ROWS, 64), jnp.float32),
    )(x, w)


def main():
    from parallel_cnn_tpu.utils.backend import is_tpu

    if not is_tpu():
        print("mosaic_probe: needs a TPU (compiled Mosaic); current "
              "backend is not TPU — nothing probed")
        return 0
    _run("rank3-dot", probe_rank3_dot)
    _run("lane-merge", probe_lane_merge)
    _run("lane-split", probe_lane_split)
    _run("vpu-conv-baseline", probe_vpu_conv_baseline)
    _run("mxu-conv-L", probe_mxu_conv_L)
    _run("mxu-conv-3d", probe_mxu_conv_3d)
    _run("pair-dot-laneslice", probe_pair_dot_laneslice)
    _run("two-dot-baseline", probe_two_dot_baseline)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
