// Native idx-ubyte MNIST loader (≙ the reference's C loader,
// Sequential/mnist.h:79-160, byte-identical across its four backends).
//
// Same format + error-code contract as mnist_load():
//   magic 2051 (images) / 2049 (labels), big-endian u32 header fields
//   (mnist.h:60-71,100-110), 28x28 validation (:128-131), /255.0 pixel
//   scaling (:143-146); 0 on success, negative codes on failure
//   (-1 missing file, -2 bad image file, -3 bad label file, -4 count
//   mismatch — mnist.h:96-121).
//
// Unlike the reference (per-sample fread into one struct per image), this
// reads each file with one bulk fread and vectorizes the u8→f32 scale, then
// hands Python a caller-allocated contiguous buffer ready for
// jax.device_put. Two-phase API (count query, then fill) so the Python side
// owns all allocation — no ownership crossing the FFI boundary.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

namespace {

constexpr uint32_t kImageMagic = 2051;
constexpr uint32_t kLabelMagic = 2049;

// ≙ mnist_bin_to_int (Sequential/mnist.h:60-71): big-endian u32.
bool read_u32be(FILE* f, uint32_t* out) {
  unsigned char b[4];
  if (fread(b, 1, 4, f) != 4) return false;
  *out = (uint32_t(b[0]) << 24) | (uint32_t(b[1]) << 16) |
         (uint32_t(b[2]) << 8) | uint32_t(b[3]);
  return true;
}

struct FileCloser {
  FILE* f;
  ~FileCloser() {
    if (f) fclose(f);
  }
};

}  // namespace

extern "C" {

// Returns the image count, or a negative error code.
long pcnn_mnist_image_count(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  FileCloser closer{f};
  uint32_t magic, count, rows, cols;
  if (!read_u32be(f, &magic) || magic != kImageMagic) return -2;
  if (!read_u32be(f, &count) || !read_u32be(f, &rows) || !read_u32be(f, &cols))
    return -2;
  if (rows != 28 || cols != 28) return -2;
  return long(count);
}

// Fills `out` (n*28*28 floats, scaled /255) from the image file.
// n must equal pcnn_mnist_image_count(path). Returns 0 or negative code.
long pcnn_mnist_load_images(const char* path, float* out, long n) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  FileCloser closer{f};
  uint32_t magic, count, rows, cols;
  if (!read_u32be(f, &magic) || magic != kImageMagic) return -2;
  if (!read_u32be(f, &count) || !read_u32be(f, &rows) || !read_u32be(f, &cols))
    return -2;
  if (rows != 28 || cols != 28 || long(count) != n) return -2;
  const size_t total = size_t(n) * 28 * 28;
  std::vector<unsigned char> raw(total);
  if (fread(raw.data(), 1, total, f) != total) return -2;
  // True division (not reciprocal-multiply): bit-identical to both the
  // reference's /255.0 (mnist.h:143-146) and the NumPy parser.
  for (size_t i = 0; i < total; ++i) out[i] = float(raw[i]) / 255.0f;
  return 0;
}

long pcnn_mnist_label_count(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  FileCloser closer{f};
  uint32_t magic, count;
  if (!read_u32be(f, &magic) || magic != kLabelMagic) return -3;
  if (!read_u32be(f, &count)) return -3;
  return long(count);
}

// Fills `out` (n int32 labels). Returns 0 or negative code.
long pcnn_mnist_load_labels(const char* path, int32_t* out, long n) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  FileCloser closer{f};
  uint32_t magic, count;
  if (!read_u32be(f, &magic) || magic != kLabelMagic) return -3;
  if (!read_u32be(f, &count) || long(count) != n) return -3;
  std::vector<unsigned char> raw(static_cast<size_t>(n));
  if (fread(raw.data(), 1, size_t(n), f) != size_t(n)) return -3;
  for (long i = 0; i < n; ++i) out[i] = int32_t(raw[i]);
  return 0;
}

}  // extern "C"
