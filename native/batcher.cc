// Native prefetching batch pipeline — the framework's data-runtime
// component, in C++ like the reference's runtime layer.
//
// The reference has no batching at all: its training loop walks the dataset
// one sample per step in file order (Sequential/Main.cpp:154-171), and the
// CUDA backend pays a host→device copy per sample (CUDA/layer.cu:60-63,
// SURVEY.md §3.2). Here a worker thread assembles shuffled batches into a
// ring of reusable slots *while the TPU trains on the previous batch*, so
// host-side gather/shuffle time overlaps device compute and the Python side
// always finds the next contiguous batch ready for one jax.device_put.
//
// Zero-copy handoff: acquire() returns pointers into the ring slot; the
// consumer calls release() when the batch has been devic-put. Epoch
// shuffling is Fisher–Yates under a seeded xorshift64* (deterministic
// given the seed — the framework's reproducibility contract; the reference
// replays file order, which is the shuffle=false mode).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// xorshift64* — tiny, seedable, good enough for epoch permutations.
struct XorShift64 {
  uint64_t s;
  uint64_t next() {
    s ^= s >> 12;
    s ^= s << 25;
    s ^= s >> 27;
    return s * 0x2545F4914F6CDD1DULL;
  }
};

struct Slot {
  std::vector<float> x;
  std::vector<int32_t> y;
  bool ready = false;
};

struct Batcher {
  const float* images;   // borrowed; caller keeps alive (numpy array)
  const int32_t* labels; // borrowed
  long n;
  long batch;
  long sample_size;  // floats per sample (28·28 MNIST, 32·32·3 CIFAR, …)
  bool shuffle;
  XorShift64 rng;

  std::vector<Slot> ring;
  size_t head = 0;  // next slot the producer fills
  size_t tail = 0;  // next slot the consumer takes
  std::mutex mu;
  std::condition_variable cv_producer, cv_consumer;
  std::atomic<bool> stop{false};
  std::thread worker;

  std::vector<long> perm;
  long cursor = 0;  // position in perm; wraps per epoch

  void reshuffle() {
    for (long i = n - 1; i > 0; --i) {
      long j = long(rng.next() % uint64_t(i + 1));
      std::swap(perm[i], perm[j]);
    }
  }

  void fill(Slot* slot) {
    for (long b = 0; b < batch; ++b) {
      if (cursor == n) {
        cursor = 0;
        if (shuffle) reshuffle();
      }
      const long src = perm[cursor++];
      std::memcpy(slot->x.data() + b * sample_size,
                  images + src * sample_size, sizeof(float) * sample_size);
      slot->y[size_t(b)] = labels[src];
    }
  }

  void run() {
    for (;;) {
      std::unique_lock<std::mutex> lock(mu);
      cv_producer.wait(lock,
                       [&] { return stop.load() || !ring[head].ready; });
      if (stop.load()) return;
      Slot* slot = &ring[head];
      lock.unlock();
      fill(slot);  // heavy copy outside the lock; slot is producer-owned
      lock.lock();
      slot->ready = true;
      head = (head + 1) % ring.size();
      cv_consumer.notify_one();
    }
  }
};

}  // namespace

extern "C" {

// images: (n, sample_size) float32 (any per-sample shape, flattened —
// 28·28 MNIST, 32·32·3 CIFAR, …), labels: (n,) int32 — borrowed for the
// batcher's lifetime. depth = ring slots (≥2 for overlap).
void* pcnn_batcher_create(const float* images, const int32_t* labels, long n,
                          long sample_size, long batch, long depth,
                          uint64_t seed, int shuffle) {
  // batch > n would wrap the cursor mid-batch and silently duplicate
  // samples within one batch (reshuffling mid-batch under shuffle).
  if (n <= 0 || sample_size <= 0 || batch <= 0 || batch > n || depth < 1)
    return nullptr;
  auto* b = new Batcher();
  b->images = images;
  b->labels = labels;
  b->n = n;
  b->batch = batch;
  b->sample_size = sample_size;
  b->shuffle = shuffle != 0;
  b->rng.s = seed ? seed : 0x9E3779B97F4A7C15ULL;
  b->ring.resize(size_t(depth));
  for (auto& slot : b->ring) {
    slot.x.resize(size_t(batch) * size_t(sample_size));
    slot.y.resize(size_t(batch));
  }
  b->perm.resize(size_t(n));
  for (long i = 0; i < n; ++i) b->perm[size_t(i)] = i;
  if (b->shuffle) b->reshuffle();
  b->worker = std::thread([b] { b->run(); });
  return b;
}

// Blocks until the next batch is ready; hands out slot pointers (valid
// until the matching release). Returns 0, or -1 after destroy.
long pcnn_batcher_acquire(void* handle, float** out_x, int32_t** out_y) {
  auto* b = static_cast<Batcher*>(handle);
  std::unique_lock<std::mutex> lock(b->mu);
  b->cv_consumer.wait(lock,
                      [&] { return b->stop.load() || b->ring[b->tail].ready; });
  if (b->stop.load()) return -1;
  Slot& slot = b->ring[b->tail];
  *out_x = slot.x.data();
  *out_y = slot.y.data();
  return 0;
}

// Marks the current batch consumed; its pointers become invalid.
void pcnn_batcher_release(void* handle) {
  auto* b = static_cast<Batcher*>(handle);
  {
    std::lock_guard<std::mutex> lock(b->mu);
    b->ring[b->tail].ready = false;
    b->tail = (b->tail + 1) % b->ring.size();
  }
  b->cv_producer.notify_one();
}

void pcnn_batcher_destroy(void* handle) {
  auto* b = static_cast<Batcher*>(handle);
  {
    // stop must be stored under mu: a thread that has evaluated its wait
    // predicate (false) but not yet blocked would otherwise miss the
    // notify — a lost wakeup that parks the worker forever and hangs
    // worker.join() below.
    std::lock_guard<std::mutex> lock(b->mu);
    b->stop.store(true);
  }
  b->cv_producer.notify_one();
  b->cv_consumer.notify_one();
  if (b->worker.joinable()) b->worker.join();
  delete b;
}

}  // extern "C"
